"""Batch normalization with explicit replica-group semantics.

The reference trained per-replica BN (each worker normalized with its own
shard's moments — an implicit consequence of graph-per-worker data
parallelism) and attributed its distributed accuracy gap to it (reference
README.md:38,54). Under ``jit`` over a sharded batch the natural semantics
flip: moments are global (XLA all-reduces the mean), i.e. cross-replica BN.

To support BOTH numerics — cross-replica (better accuracy) and per-replica
(reference-faithful comparison) — this module computes moments over
configurable batch *groups*:

  * ``groups=1``  → one global moment set: cross-replica BN. When the batch
    is sharded over the mesh the mean is a cross-device ``all-reduce`` XLA
    lays on ICI.
  * ``groups=G``  → the batch is viewed as G equal groups, each normalized
    with its own moments. With G = number of batch shards and a
    shard-aligned leading dim, each group is exactly one device's shard, so
    XLA needs NO collective and the numerics equal the reference's
    per-replica BN — deterministically, on any mesh size.

Running statistics are always aggregated globally (mean of group means with
the between-group variance correction), matching what a synced-checkpoint
evaluator expects.

Performance: moments and affine coefficients are computed in float32, but the
per-element application is a single fused multiply-add in the COMPUTE dtype —
``y = x * a + b`` with ``a = scale·rsqrt(var+eps)`` and ``b = bias − mean·a``
— so the bandwidth-bound elementwise pass runs at bf16 VPU rate and XLA can
fuse it into the surrounding conv. Momentum 0.997 / eps 1e-5 defaults mirror
reference resnet_model_official.py:37-38. ``axis_name`` additionally pmean's
moments across a named axis for ``shard_map``/``pmap`` callers.

The BN training tax — ~38% of the ImageNet ResNet-50 step is per-channel
reduction passes over the activations — was attacked four ways in round 3
(docs/perf_imagenet_r3.md has the measured table): a custom_vjp with
hand-scheduled minimal passes (parity — XLA's autodiff already multi-output-
fuses the paired reduces), a variadic ``lax.reduce`` (slower: bad TPU
lowering), streaming Pallas reduction kernels (much slower: per-call
overhead ≫ bandwidth saved at these sizes), and moment subsampling. Only
the last is kept: ``stat_subsample=s`` estimates the batch moments from the
CONTIGUOUS center band of H/s rows (a strided ::s lattice gathers and
measured slower than the full reduce; a band is a zero-copy prefix read and
its gradient a fused pad). It is ~neutral at bs=128 on one v5e — the stat
pass it trims is only ~15% of the step — but scales with batch and spatial
size; default 1 (exact reference numerics). Normalization, gradients and
running averages all use the band moments, so autodiff yields the exact
gradient of the band-stat forward.
"""
from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax


def _band(x: jax.Array, sub: int) -> jax.Array:
    """Center band of H/sub rows (axis 1) — the contiguous stat sample."""
    if sub <= 1 or x.ndim != 4:
        return x
    h = x.shape[1]
    bh = max(1, h // sub)
    lo = (h - bh) // 2
    return lax.slice_in_dim(x, lo, lo + bh, axis=1)


class GroupedBatchNorm(nn.Module):
    momentum: float = 0.997
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16
    groups: int = 1
    axis_name: Optional[str] = None
    use_scale: bool = True
    use_bias: bool = True
    # >1: estimate batch moments from the center band of H/s rows (see
    # module docstring); 1 = exact moments (default, reference numerics)
    stat_subsample: int = 1

    @nn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((features,), jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32) if self.use_scale else None
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32) if self.use_bias else None

        one = jnp.ones((features,), jnp.float32)
        zero = jnp.zeros((features,), jnp.float32)
        scale_f = scale if scale is not None else one
        bias_f = bias if bias is not None else zero

        def affine(mean, var):
            """f32 (…,C) moments → bf16 fused y = x·a + b."""
            a = scale_f * jax.lax.rsqrt(var + self.epsilon)
            b = bias_f - mean * a
            return a, b

        if not train:
            a, b = affine(ra_mean.value, ra_var.value)
            return (x * a.astype(x.dtype) + b.astype(x.dtype)).astype(self.dtype)

        g = self.groups
        reduce_axes = tuple(range(x.ndim - 1))  # all but channels
        s = self.stat_subsample
        # moments come from xs (the stat sample); normalization applies to x
        xs = _band(x, s)
        if g > 1:
            bsz = x.shape[0]
            if bsz % g != 0:
                raise ValueError(f"batch {bsz} not divisible by bn groups {g}")
            xg = x.reshape((g, bsz // g) + x.shape[1:])
            xsg = xs.reshape((g, bsz // g) + xs.shape[1:])
            xf = xsg.astype(jnp.float32)
            gaxes = tuple(range(1, xsg.ndim - 1))
            gmean = jnp.mean(xf, axis=gaxes)                       # (g, C)
            gsq = jnp.mean(jnp.square(xf), axis=gaxes)
            if self.axis_name is not None:
                # pmean the RAW moments (E[x], E[x²]), not the centered
                # variance: averaging per-shard variances would drop the
                # between-shard mean spread and understate var — the
                # shard_map path (parallel/overlap.py) must match the jit
                # path's global moments
                gmean = jax.lax.pmean(gmean, self.axis_name)
                gsq = jax.lax.pmean(gsq, self.axis_name)
            gvar = gsq - jnp.square(gmean)
            a, b = affine(gmean, gvar)                             # (g, C)
            bshape = (g,) + (1,) * (xg.ndim - 2) + (features,)
            y = xg * a.reshape(bshape).astype(x.dtype) + \
                b.reshape(bshape).astype(x.dtype)
            y = y.reshape(x.shape)
            # global stats for the running averages: law of total variance
            mean = jnp.mean(gmean, axis=0)
            var = jnp.mean(gvar + jnp.square(gmean), axis=0) - jnp.square(mean)
        else:
            xf = xs.astype(jnp.float32)
            mean = jnp.mean(xf, axis=reduce_axes)
            msq = jnp.mean(jnp.square(xf), axis=reduce_axes)
            if self.axis_name is not None:
                # raw moments, not centered variance — see the grouped
                # branch above; with axis_name=None the expression below
                # is bit-identical to the previous var formula
                mean = jax.lax.pmean(mean, self.axis_name)
                msq = jax.lax.pmean(msq, self.axis_name)
            var = msq - jnp.square(mean)
            a, b = affine(mean, var)
            y = x * a.astype(x.dtype) + b.astype(x.dtype)

        m = self.momentum
        if not self.is_initializing():
            ra_mean.value = m * ra_mean.value + (1 - m) * mean
            ra_var.value = m * ra_var.value + (1 - m) * var
        return y.astype(self.dtype)


def effective_gn_groups(channels: int, groups: int) -> int:
    """Largest valid group count ≤ ``groups`` for ``channels``: min(G, C)
    when it divides C, else gcd(G, C). Keeps the published G=32 on every
    ImageNet stage (64..2048 channels) and degrades deterministically on
    narrow CIFAR stages (16 → 16 groups)."""
    if groups < 1:
        raise ValueError(f"gn_groups must be >= 1, got {groups}")
    g = min(groups, channels)
    if channels % g:
        g = math.gcd(groups, channels) or 1
    return g


class ChannelGroupNorm(nn.Module):
    """GroupNorm (Wu & He 2018) over channel groups — the BN-free training
    contract (``model.norm='group'``).

    Batch-independent by construction: moments are per (sample, group) over
    (H, W, C/G), so there is NO cross-replica collective, no running
    statistics to checkpoint, and no train/eval numerics split — the
    properties BatchNorm costs this framework (the per-channel stat passes
    are ~38% of the faithful-BN ImageNet step, docs/perf_imagenet_r3.md,
    and the distributed moment semantics are the accuracy bug the reference
    documented, reference README.md:38,54).

    Same fused-application shape as GroupedBatchNorm: f32 moments and
    affine coefficients, one bf16 multiply-add per element (a/b broadcast
    as (N, 1, 1, C)) that XLA fuses into the surrounding conv."""

    groups: int = 32
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        del train  # stateless — identical in train and eval
        c = x.shape[-1]
        g = effective_gn_groups(c, self.groups)
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        n = x.shape[0]
        xg = x.reshape((n,) + x.shape[1:-1] + (g, c // g)).astype(jnp.float32)
        axes = tuple(range(1, xg.ndim - 2)) + (xg.ndim - 1,)
        mean = jnp.mean(xg, axis=axes)                        # (N, G)
        var = jnp.mean(jnp.square(xg), axis=axes) - jnp.square(mean)
        rstd = lax.rsqrt(var + self.epsilon)                  # (N, G)
        # per-sample per-channel fused coefficients: broadcast (N,G) over
        # the C/G channels of each group, fold in the learned affine
        a = (scale.reshape(g, c // g)[None] * rstd[..., None]).reshape(n, c)
        b = (bias.reshape(g, c // g)[None]
             - mean[..., None] * scale.reshape(g, c // g)[None]
             * rstd[..., None]).reshape(n, c)
        bshape = (n,) + (1,) * (x.ndim - 2) + (c,)
        y = x * a.reshape(bshape).astype(x.dtype) \
            + b.reshape(bshape).astype(x.dtype)
        return y.astype(self.dtype)
