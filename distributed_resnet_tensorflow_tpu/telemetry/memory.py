"""Device-memory telemetry: turn OOMs from postmortems into trends.

Nothing in the framework measured memory at runtime: an HBM OOM surfaced
as an XLA allocation error after hours, host-RSS creep (a leaking decode
pool, an unbounded cache) as a SLURM OOM-kill, and neither left a trend
line to read back. This module is the sampler behind the registered
``{"event": "memory"}`` rows (utils.metrics.EVENT_SCHEMAS):

  * **device side** — live ``jax.Array`` bytes per addressable device
    (``jax.live_arrays()``: portable, works on the CPU test mesh), plus
    the allocator's ``memory_stats()`` (``bytes_in_use`` /
    ``peak_bytes_in_use`` / ``bytes_limit``) where the backend reports it
    (TPU); the allocator peak is authoritative where present, the
    live-array watermark is the portable fallback. The watermark is
    SAMPLED — a spike between samples is invisible; that limitation is
    exactly why the allocator stats ride along when available.
  * **host side** — ``VmRSS`` / ``VmHWM`` from ``/proc/self/status``.
  * **pipeline occupancy** — the decoded-sample echo cache
    (utils.metrics.echo_stats) and the coalesced staging rings
    (parallel/sharding.staging_occupancy), the two byte-bounded host
    pools a mis-sized config silently grows into.

Sampled at the train-loop summary cadence (train/hooks.MemoryHook, every
process — each host owns its devices) and the serve report cadence
(serve/server.py); ``main.py monitor`` rolls the per-host HBM watermark
up with a warn threshold (docs/observability.md).
"""
from __future__ import annotations

import logging
import threading
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)


class MemoryWatermarks:
    """Process-global sampled high-water marks (per device + total):
    ``update`` folds one sample in and returns the running peaks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._peak_by_device: Dict[str, int] = {}
        self._peak_total = 0

    def update(self, live_by_device: Dict[str, int]) -> Dict[str, Any]:
        total = sum(live_by_device.values())
        with self._lock:
            for dev, n in live_by_device.items():
                if n > self._peak_by_device.get(dev, 0):
                    self._peak_by_device[dev] = n
            self._peak_total = max(self._peak_total, total)
            return {"by_device": dict(self._peak_by_device),
                    "total": self._peak_total}

    def reset(self) -> None:
        with self._lock:
            self._peak_by_device.clear()
            self._peak_total = 0


#: the process-global watermark tracker every sampler feeds
watermarks = MemoryWatermarks()


def _live_bytes_by_device() -> Dict[str, int]:
    """Live jax.Array bytes per addressable device. O(live arrays) — a
    summary-cadence cost, not a hot-path one."""
    import jax
    out: Dict[str, int] = {str(d.id): 0 for d in jax.local_devices()}
    for arr in jax.live_arrays():
        try:
            for shard in arr.addressable_shards:
                key = str(shard.device.id)
                if key in out:
                    out[key] += int(shard.data.nbytes)
        except Exception:  # a deleted/donated array mid-scan
            continue
    return out


def _host_rss() -> Dict[str, int]:
    """VmRSS/VmHWM in bytes from /proc/self/status; empty off-Linux."""
    out: Dict[str, int] = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    out["host_rss_bytes"] = int(line.split()[1]) * 1024
                elif line.startswith("VmHWM:"):
                    out["host_peak_rss_bytes"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    return out


def sample_memory(process_index: Optional[int] = None) -> Dict[str, Any]:
    """One ``{"event": "memory"}`` payload (minus the event/step keys the
    exporting hook adds). Never raises — telemetry must not kill the
    run; a failed probe degrades to fewer fields."""
    import jax
    row: Dict[str, Any] = {}
    try:
        row["process"] = jax.process_index() if process_index is None \
            else int(process_index)
    except Exception:
        row["process"] = int(process_index or 0)
    try:
        live = _live_bytes_by_device()
        peaks = watermarks.update(live)
        devices: Dict[str, Dict[str, int]] = {
            dev: {"live_bytes": n,
                  "live_peak_bytes": peaks["by_device"].get(dev, n)}
            for dev, n in live.items()}
        for d in jax.local_devices():
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:  # backend without allocator stats
                stats = None
            if stats:
                cell = devices.setdefault(str(d.id), {})
                for src, dst in (("bytes_in_use", "bytes_in_use"),
                                 ("peak_bytes_in_use", "peak_bytes_in_use"),
                                 ("bytes_limit", "bytes_limit")):
                    if src in stats:
                        cell[dst] = int(stats[src])
        row["devices"] = devices
        row["live_bytes_total"] = sum(live.values())
        row["live_peak_bytes_total"] = peaks["total"]
    except Exception:  # pragma: no cover - observability best effort
        log.exception("device-memory sample failed")
    row.update(_host_rss())
    try:
        from ..utils.metrics import echo_stats
        row["echo_cache_bytes"] = echo_stats.cache_bytes
        row["echo_cache_cap_bytes"] = echo_stats.cache_cap_bytes
    except Exception:  # pragma: no cover
        pass
    try:
        from ..parallel.sharding import staging_occupancy
        slots, inflight = staging_occupancy()
        row["staging_ring_slots"] = slots
        row["staging_ring_inflight"] = inflight
    except Exception:  # pragma: no cover
        pass
    return row
