"""Lint rule registry — one module per invariant.

Each rule module exposes ``RULE_NAME`` (the id findings carry and
suppressions name), ``DOC`` (one paragraph: the invariant and the
incident it encodes), and ``check(ctx) -> Iterable[Finding]``.
Adding a rule: create the module, append it to ``ALL_RULES``, add a
known-bad fixture to tests/test_analysis.py and a row to the catalog in
docs/static_analysis.md.
"""
from . import (bare_assert, blocking_call, cached_mesh, chief_collective,
               ckpt_io, device_put, exit_codes, lock_order, opt_state,
               precision_cast, protocol_drift, registry_drift,
               thread_dispatch)

ALL_RULES = (
    device_put,
    cached_mesh,
    bare_assert,
    exit_codes,
    registry_drift,
    ckpt_io,
    opt_state,
    precision_cast,
    thread_dispatch,
    blocking_call,
    chief_collective,
    lock_order,
    protocol_drift,
)

#: the hangcheck thread/lock contract rules (ISSUE 13) — ``main.py check
#: --no-hangcheck`` excludes exactly these (mirroring --no-zero1-sweep)
HANGCHECK_RULES = (
    thread_dispatch,
    blocking_call,
    chief_collective,
    lock_order,
)

#: the protocol-model conformance rules (ISSUE 20) — ``main.py check
#: --no-protocol`` excludes these alongside skipping the model-checking
#: phase itself
PROTOCOL_RULES = (
    protocol_drift,
)
