"""The inference server: AOT cache + dynamic batcher + hot swap, composed.

``InferenceServer`` owns a Trainer (model + mesh + shardings — the same
construction path training and eval use), serves single-example requests
through the dynamic batcher, and follows the training run's checkpoint
directory via the hot-swap thread. ``main.py serve`` builds one, optionally
drives the open-loop load generator against it, and prints a JSON report
(p50/p99 latency and QPS per bucket).

Threading recap (docs/serving.md has the diagram):
  * submitter threads — numpy in, Future out (``submit``);
  * ONE dispatch thread — stages batches through the Trainer's put path
    (CoalescedStager on accelerators), finalizes, executes the
    AOT-compiled predict, resolves futures, applies pending swaps at batch
    boundaries;
  * swap thread — filesystem + host deserialization only.
The dispatch sanitizer (PR 5) passes over this arrangement by
construction; ``scripts/serve_smoke.sh`` runs with it armed.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from concurrent.futures import Future
from typing import Optional, Tuple

import jax
import numpy as np

from ..telemetry.tracer import span
from ..train.loop import Trainer
from ..utils.config import ExperimentConfig, resolve_checkpoint_dir
from ..utils.metrics import LatencyStats, MetricsWriter
from .batcher import DynamicBatcher
from .compile_cache import ServeCompileCache, bucket_sizes
from .swap import CheckpointSwapper, PendingSwap

log = logging.getLogger(__name__)

#: dispatch batches between ``{"event": "memory"}`` samples
#: (telemetry.memory gates the rows entirely): coarse enough that the
#: live_arrays scan never shows in serve tail latency, fine enough that a
#: leak over a day-long run has hundreds of trend points
_MEMORY_EVERY_BATCHES = 50


def serve_stream_dir(cfg: ExperimentConfig) -> str:
    """Where this serving process keeps its metrics stream / READY marker
    / swap pin: ``<log_root>/serve`` standalone, ``<log_root>/serve-r<id>``
    as a fleet replica (matches serve/fleet.replica_dir so supervisor and
    replica agree without talking)."""
    sub = "serve" if cfg.serve.replica_id < 0 \
        else f"serve-r{cfg.serve.replica_id}"
    return os.path.join(cfg.log_root, sub)


def serve_image_spec(cfg: ExperimentConfig) -> Tuple[Tuple[int, ...], type]:
    """(per-example shape, dtype) of a serving request — must match what
    the eval input pipeline would deliver, because the predict step shares
    the eval step's prep contract (make_predict_step): imagenet with
    device-side standardize takes raw uint8 crops, everything else
    host-prepped float32."""
    from ..data import device_augment_enabled
    if cfg.model.name == "logistic":
        return (cfg.model.input_size,), np.float32
    s = cfg.data.image_size
    if cfg.data.dataset == "imagenet" and device_augment_enabled(cfg, "eval"):
        return (s, s, 3), np.uint8
    return (s, s, 3), np.float32


class InferenceServer:
    """Batched, hot-swappable inference over a training run's checkpoints.

    Single-process (a serving replica is one jax world; fleet-level
    replication is the launcher's job). ``start()`` restores the newest
    committed checkpoint (if any), AOT-warms every (bucket, variant),
    then starts the dispatch + swap threads; ``submit()`` returns a
    Future resolving to ``(logits_row, served_step)``.
    ``start(start_threads=False)`` leaves the threads off for
    deterministic single-thread driving (``service_once`` — tests, bench
    warm paths).

    Variants (``serve.variants``; docs/precision.md): each configured
    precision variant ("bf16") carries its own weight copy cast from the
    f32 masters and its own AOT bucket programs; requests pick one at
    ``submit(variant=...)`` and hot swaps rebuild every variant from the
    newly restored masters, so no variant can lag a checkpoint behind.
    """

    def __init__(self, cfg: ExperimentConfig,
                 writer: Optional[MetricsWriter] = None, mesh=None):
        if jax.process_count() > 1:
            raise NotImplementedError(
                "serve/ is single-process per replica; run one server per "
                "host and load-balance above them")
        self.cfg = cfg
        self.writer = writer
        self.trainer = Trainer(cfg, mesh=mesh)
        self.trainer.init_state()
        # serving precision variants (docs/precision.md): every variant
        # keeps its own weight copy cast from the f32 masters + its own
        # AOT programs; the FIRST is the default a variant-less request
        # gets. The f32 masters themselves live on the trainer state —
        # variants are rebuilt from them at every (startup/hot) swap.
        from ..parallel.precision import (make_variant_cast,
                                          resolve_serve_variants)
        self.variants = resolve_serve_variants(cfg)
        self._variant_casts = {v: make_variant_cast(v)
                               for v in self.variants}
        # the f32 MASTER state every variant casts from — kept even when
        # "f32" is not a served variant (swap validation compares
        # checkpoints against the masters, never a cast copy). Variant
        # weight copies are built LAZILY (start() after the restore
        # attempt, or first dispatch): casting fresh-init params that a
        # startup restore immediately replaces would waste a per-leaf
        # device cast and transient HBM per non-f32 variant.
        self._master_state = self.trainer.state
        self._states = None
        self.serving_step = -1  # -1 = fresh init, no checkpoint applied
        self.image_shape, self.image_dtype = serve_image_spec(cfg)
        max_batch = cfg.serve.max_batch or cfg.data.eval_batch_size
        self.buckets = bucket_sizes(max_batch,
                                    self.trainer.eval_pad_multiple())
        variant_predicts = {
            v: self.trainer.make_variant_predict_step(v)
            for v in self.variants if v != "f32"}
        if "f32" in self.variants and self.trainer.precision_active:
            # the f32 variant is the FULL-PRECISION oracle even when the
            # serving config carries a bf16 TRAINING policy: the
            # trainer's own predict step computes in the policy dtype,
            # so the f32 variant needs its own f32-compute program
            variant_predicts["f32"] = \
                self.trainer.make_variant_predict_step("f32")
        self.cache = ServeCompileCache(self.trainer,
                                       variant_predicts=variant_predicts)
        self.latency = LatencyStats()
        # fleet mode: swaps follow the router's per-replica pin file
        # (canary/rollback control) instead of chasing the newest commit
        gate = os.path.join(serve_stream_dir(cfg), "SWAP_CONTROL.json") \
            if cfg.serve.swap_gate else None
        self.swapper = CheckpointSwapper(
            resolve_checkpoint_dir(cfg),
            poll_secs=cfg.serve.poll_interval_secs,
            on_reject=self._on_swap_reject,
            seed=cfg.serve.load_seed,
            gate_path=gate)
        self.batcher = DynamicBatcher(
            self.buckets, self._run_bucket, self.image_shape,
            self.image_dtype,
            max_queue_delay_ms=cfg.serve.max_queue_delay_ms,
            boundary_hook=self._apply_pending_swap,
            variants=self.variants)
        self.completed = 0
        self.swaps = 0
        self._t_start = time.monotonic()
        self._closed = False
        self._batches_since_mem = 0  # serve-side memory-row cadence
        # fleet chaos knobs (DRT_FAULT_SERVE_*, scoped by replica id) —
        # inert unless armed; fired at the top of every dispatch batch
        from ..resilience.faultinject import ServeFaults
        self._faults = ServeFaults.from_env(cfg.serve.replica_id)
        # optional HeartbeatPublisher a fleet replica's run loop attaches
        # (main.py run_serve); the dispatch thread updates step/progress
        # so a wedged dispatch shows as frozen progress with live beats
        self.heartbeat = None

    # -- lifecycle ---------------------------------------------------------
    def start(self, start_threads: bool = True) -> "InferenceServer":
        # initial restore runs the swap machinery ONCE on the caller
        # thread (host restore + device_put — no multi-device execution,
        # so thread ownership is not claimed here): the newest committed
        # step that VERIFIES — older good checkpoints beat serving random
        # params when the single newest commit is torn. take_pending()
        # CLAIMS the parked swap — otherwise the dispatch thread's first
        # boundary hook would re-apply the same checkpoint a second time
        pending = self.swapper.take_pending() \
            if self.swapper.restore_newest_valid() is not None else None
        if pending is not None:
            self._apply_swap(pending)  # builds the variant states
            # `swaps` counts HOT swaps (a checkpoint published while
            # serving): the startup restore is not one, and counting it
            # would let the smoke's "a hot swap landed" assertion pass
            # with hot swap entirely broken
            self.swaps = 0
        else:
            log.warning(
                "serve: no usable committed checkpoint in %s — serving "
                "freshly initialized params until a training run "
                "publishes one", self.swapper.directory)
        if self._states is None:  # no restore landed: cast the init state
            self._states = self._build_variant_states(self._master_state)
        if self.cfg.serve.warm_buckets:
            warm = self.cache.warm(self.buckets, self.image_shape,
                                   self.image_dtype,
                                   variants=self.variants)
            log.info("serve: %d bucket(s) %s × %d variant(s) %s "
                     "AOT-compiled in %.1fs", len(self.buckets),
                     self.buckets, len(self.variants),
                     list(self.variants), warm)
        if start_threads:
            # a jitted state init already ran on this (caller) thread; the
            # dispatch thread owns all multi-device executions from here on
            # — tell an armed sanitizer this is a legitimate handoff
            from ..analysis import dispatch_sanitizer as _ds
            if _ds.is_installed():
                _ds.reset_owner()
            self.batcher.start()
            self.swapper.start()
        self._t_start = time.monotonic()
        return self

    def close(self) -> None:
        """Drain + stop: intake closes first, every accepted request is
        answered before the dispatch thread exits (zero dropped), then the
        swap thread stops. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self.batcher.close()
        self.swapper.close()
        self._write_request_summary()

    # -- variant states ----------------------------------------------------
    def _build_variant_states(self, f32_state):
        """Cast the f32 master state into every configured variant's
        weight copy (parallel/precision.make_variant_cast). Runs on the
        thread that owns dispatch at the time: the caller thread during
        __init__/startup (before the dispatch thread exists), the
        dispatch thread at hot-swap boundaries."""
        out = {}
        for v in self.variants:
            with span("serve.variant_build", variant=v):
                out[v] = self._variant_casts[v](f32_state)
        return out

    # -- request path ------------------------------------------------------
    def submit(self, image, variant: Optional[str] = None) -> Future:
        """One example in, Future of ``(logits_row, served_step)`` out.
        ``variant`` picks the serving precision variant (None = the
        configured default; unknown names are rejected loudly)."""
        return self.batcher.submit(image, variant=variant)

    def service_once(self, block_secs: float = 0.0) -> int:
        """Single synchronous service turn on the calling thread (see
        DynamicBatcher.service_once) — deterministic tests/embedding."""
        return self.batcher.service_once(block_secs)

    def _run_bucket(self, images: np.ndarray, group) -> None:
        """Dispatch-thread only: stage → finalize → compiled predict →
        resolve futures. ``images`` is already padded to its bucket; the
        group is single-variant by the batcher's collection contract."""
        from ..parallel.sharding import finalize_staged
        self._faults.maybe_fire(self.batcher.batches + 1, self.serving_step)
        if self.heartbeat is not None:
            self.heartbeat.update(step=max(0, self.serving_step))
        t0 = time.perf_counter()
        bucket = images.shape[0]
        variant = group[0].variant
        if self._states is None:
            # dispatch before start() (thread-less embedding driving the
            # batcher directly): build here, on the thread that owns
            # dispatch by definition
            self._states = self._build_variant_states(self._master_state)
        with span("serve.batch", bucket=bucket, n=len(group),
                  variant=variant):
            compiled = self.cache.get(bucket, self.image_shape,
                                      self.image_dtype, variant=variant)
            # the Trainer's put path: CoalescedStager on accelerators (one
            # batched transfer issue), per-leaf device_put fallback on CPU;
            # finalize (a multi-device execution) stays on THIS thread
            dev = finalize_staged(self.trainer._put_batch({"images": images}))
            logits = np.asarray(compiled(self._states[variant], dev))
        t1 = time.perf_counter()
        step = self.serving_step
        # latency keys carry the variant past the default f32 — the
        # (batch, variant) breakdown bench's serving row reports; plain
        # f32 keys keep their historical names
        key = f"bucket_{bucket}" if variant == "f32" \
            else f"bucket_{bucket}_{variant}"
        for i, req in enumerate(group):
            req.future.set_result((logits[i], step))
            self.latency.record(key, t1 - req.t_submit)
        self.completed += len(group)
        if self.writer is not None:
            self.writer.write_event("serve_batch", {
                "step": step, "bucket": bucket, "n": len(group),
                "variant": variant,
                "queue_ms": round((t0 - group[0].t_submit) * 1000.0, 3),
                "run_ms": round((t1 - t0) * 1000.0, 3)})
            self._batches_since_mem += 1
            if self._batches_since_mem >= _MEMORY_EVERY_BATCHES:
                self._write_memory_row()

    def _write_memory_row(self) -> None:
        """One ``{"event": "memory"}`` sample (telemetry/memory.py) from
        the serving process — HBM/RSS trend lines for a server that runs
        for days, at the batch cadence so an idle server stays silent."""
        if self.writer is None or not self.cfg.telemetry.memory:
            return
        self._batches_since_mem = 0
        from ..telemetry.memory import sample_memory
        self.writer.write_event("memory", {"step": self.serving_step,
                                           **sample_memory()})

    # -- hot swap ----------------------------------------------------------
    def _apply_pending_swap(self) -> None:
        """Batch-boundary hook (dispatch thread): apply a restored
        checkpoint atomically between batches."""
        pending = self.swapper.take_pending()
        if pending is not None:
            self._apply_swap(pending)

    def _apply_swap(self, pending: PendingSwap) -> None:
        with span("serve.swap_apply", step=pending.step):
            self._apply_swap_inner(pending)

    def _apply_swap_inner(self, pending: PendingSwap) -> None:
        from ..parallel.sharding import put_to_sharding
        t0 = time.perf_counter()
        # validate against the F32 MASTER state: checkpoints always
        # persist f32 masters (docs/precision.md), so the shape/dtype
        # check must not compare against a cast variant's bf16 leaves
        live = self._master_state

        def check_leaf(host_leaf, live_leaf):
            # validate BEFORE any placement: a same-structure checkpoint
            # from a different model config (other num_classes/width)
            # would device_put fine and then blow up the AOT-compiled
            # executable on EVERY subsequent request — reject it here
            # instead, with the offending shapes
            hs, hd = np.shape(host_leaf), np.asarray(host_leaf).dtype
            if hs != live_leaf.shape or hd != live_leaf.dtype:
                raise ValueError(
                    f"checkpoint leaf {hs}/{hd} != serving model "
                    f"{live_leaf.shape}/{live_leaf.dtype}")
            return host_leaf

        try:
            # tree_map also raises on structure mismatch
            jax.tree_util.tree_map(check_leaf, pending.params, live.params)
            jax.tree_util.tree_map(check_leaf, pending.batch_stats,
                                   live.batch_stats)
            params_sh = jax.tree_util.tree_map(lambda x: x.sharding,
                                               live.params)
            bs_sh = jax.tree_util.tree_map(lambda x: x.sharding,
                                           live.batch_stats)
            new_params = put_to_sharding(pending.params, params_sh)
            new_bs = put_to_sharding(pending.batch_stats, bs_sh)
        except Exception as e:
            # a structure/shape mismatch (checkpoint from a different
            # model/config sharing the directory) must not take the
            # replica down — keep serving the old params, loudly
            self.swapper.rejected += 1
            log.exception("serve swap: checkpoint step %d does not fit the "
                          "serving model — keeping current params",
                          pending.step)
            self._on_swap_reject(pending.step,
                                 f"state mismatch: {type(e).__name__}: {e}")
            return
        new_step = put_to_sharding(
            np.asarray(pending.step, np.asarray(live.step).dtype),
            live.step.sharding)
        old = self.serving_step
        # one reference assignment = the atomic swap: the dispatch thread
        # is the only reader on the request path, and it is HERE, between
        # batches — in-flight requests completed on the old states, the
        # next batch reads `self._states`. EVERY variant rebuilds from
        # the new f32 masters (the cast is the swap's only extra cost),
        # so no variant can serve a stale checkpoint
        self._master_state = live.replace(step=new_step, params=new_params,
                                          batch_stats=new_bs)
        self._states = self._build_variant_states(self._master_state)
        self.serving_step = int(pending.step)
        self.swaps += 1
        apply_ms = (time.perf_counter() - t0) * 1000.0
        log.info("serve swap: now serving checkpoint step %d (was %s; "
                 "restore %.0fms off-path, apply %.0fms)", pending.step,
                 old if old >= 0 else "fresh init", pending.restore_ms,
                 apply_ms)
        if self.writer is not None:
            self.writer.write_event("serve_swap", {
                "from_step": old, "to_step": pending.step,
                "digest": pending.digest,
                "restore_ms": round(pending.restore_ms, 1),
                "apply_ms": round(apply_ms, 1)})

    def _on_swap_reject(self, step: int, reason: str) -> None:
        if self.writer is not None:
            self.writer.write_event("serve_swap", {
                "from_step": self.serving_step, "rejected": reason,
                "to_step_attempted": step})

    # -- reporting ---------------------------------------------------------
    @property
    def dropped(self) -> int:
        """Accepted requests not yet answered (contract after close: 0)."""
        done = self.completed + self.batcher.failed_requests
        return max(0, self.batcher.requests_in - done)

    def _write_request_summary(self) -> None:
        if self.writer is not None and self.batcher.requests_in:
            self.writer.write_event("serve_request", {
                "step": self.serving_step,
                "requests": self.completed, "dropped": self.dropped,
                "buckets": self.latency.summary_ms()})
            self._write_memory_row()  # the run's closing watermark

    def report(self) -> dict:
        """Snapshot report (pure read — the serve_request metrics row is
        written by close(), so report() stays callable after teardown)."""
        wall = max(time.monotonic() - self._t_start, 1e-9)
        return {
            "serving_step": self.serving_step,
            "variants": list(self.variants),
            "requests": self.batcher.requests_in,
            "completed": self.completed,
            "dropped": self.dropped,
            "errors": self.batcher.errors,
            "batches": self.batcher.batches,
            "qps": round(self.completed / wall, 1),
            "swaps": self.swaps,
            "rejected_swaps": self.swapper.rejected,
            "buckets": self.buckets,
            "latency_by_bucket_ms": self.latency.summary_ms(),
            "compile": {
                "warm_secs": round(self.cache.warm_secs, 2),
                "serve_time_compiles": self.cache.serve_time_compiles,
            },
        }
