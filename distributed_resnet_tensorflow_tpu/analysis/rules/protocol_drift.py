"""protocol-drift: the declared protocol specs must match the code.

The protocol models (analysis/protocol/, docs/static_analysis.md) are
only worth checking if they stay bound to the implementations they
model. Three resolutions per registered :class:`ProtocolSpec`:

  * every declared implementation **literal** (health-state string,
    marker-file name, control-file field, round-file prefix) must still
    appear in at least one of the spec's declared source modules — a
    rename in code without a spec update is exactly the silent
    divergence that turns an exhaustive checker into false confidence;
  * every declared source **module** must still exist in the tree
    (a moved/renamed file orphans the spec);
  * every **enum_check** must agree with the declared event inventory:
    the pipe-list in the matching ``utils.metrics.EVENT_SCHEMAS`` field
    description (``"... (probe_ok | failures | ...)"``) is parsed and
    set-compared against the spec's transition-reason/action/state
    vocabulary, and every event kind the spec's ``event_edges`` table
    replays must be a declared event.

Specs whose own registration file is absent from the linted tree are
skipped — fixture trees in tests stay clean.

Findings anchor at the spec registration's file:line (the place to fix
either side of the drift).
"""
from __future__ import annotations

import re
from typing import Iterable, Optional, Tuple

from ..report import Finding

RULE_NAME = "protocol-drift"
DOC = __doc__

#: enum pipe-lists live in EVENT_SCHEMAS field-description TEXT, either
#: parenthesized ("what moved it (probe_ok | failures | ...)") or as the
#: whole description ("start | promote | rollback")
_PAREN_ENUM_RE = re.compile(r"\(([^()]*\|[^()]*)\)")


def _declared_enum(event: str, field_name: str) -> Optional[Tuple[str, ...]]:
    from ...utils.metrics import EVENT_SCHEMAS
    desc = EVENT_SCHEMAS.get(event, {}).get("fields", {}).get(field_name)
    if not isinstance(desc, str) or "|" not in desc:
        return None
    m = _PAREN_ENUM_RE.search(desc)
    body = m.group(1) if m else desc
    return tuple(sorted(tok.strip() for tok in body.split("|")))


def check(ctx) -> Iterable[Finding]:
    from ..protocol.spec import load_specs
    from ...utils.metrics import EVENT_SCHEMAS

    by_rel = {sf.rel: sf for sf in ctx.all_python()}
    for spec in load_specs():
        if spec.path not in by_rel:
            continue   # fixture tree — the spec's own file isn't linted
        present = [by_rel[m] for m in spec.modules if m in by_rel]
        for mod in spec.modules:
            if mod not in by_rel:
                yield Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: declared module {mod!r} does not "
                    "exist in the tree — the spec is orphaned from the "
                    "implementation it models")
        for literal, what in spec.literals.items():
            if not any(literal in sf.text for sf in present):
                yield Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: declared literal {literal!r} ({what}) "
                    "appears in none of the modeled sources "
                    f"{list(spec.modules)} — the implementation moved "
                    "and the protocol spec did not")
        for kind in spec.event_edges:
            if kind not in EVENT_SCHEMAS:
                yield Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: event_edges replays {kind!r} rows but "
                    "that event is not declared in "
                    "utils.metrics.EVENT_SCHEMAS")
        for event, field_name, values in spec.enum_checks:
            declared = _declared_enum(event, field_name)
            if declared is None:
                yield Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: enum_check on {event}.{field_name} "
                    "but the EVENT_SCHEMAS field description carries no "
                    "parseable '|' enum inventory")
            elif set(declared) != set(values):
                missing = sorted(set(values) - set(declared))
                extra = sorted(set(declared) - set(values))
                yield Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: {event}.{field_name} enum drift — "
                    f"spec-only: {missing}, schema-only: {extra} "
                    "(utils.metrics.EVENT_SCHEMAS is the declared "
                    "inventory)")
