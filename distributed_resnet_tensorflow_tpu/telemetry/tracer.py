"""Flight-recorder span tracer: low-overhead per-thread spans in a ring.

The reference's only answer to "where did the wall-clock go" was offline log
scraping (SURVEY.md §2.15, §5); Horovod shipped a timeline tracer precisely
because distributed step-time mysteries cannot be debugged from scalars
(arXiv:1802.05799). This module is that capability for the framework:

  * ``span("input.wait")`` — a context manager recording one timed event
    per use into a BOUNDED in-memory ring (a crashed/wedged run holds the
    last ~N events per process, like an aircraft flight recorder). The hot
    path is two ``perf_counter`` reads plus one locked deque append — cheap
    enough to leave on in production (the bench acceptance bar is <2% on
    the CIFAR headline).
  * ``FlightRecorder.dump()`` — serialize the ring as a Chrome-trace /
    Perfetto ``trace.json`` (``{"traceEvents": [...]}``, complete "X"
    events with per-thread lanes and thread-name metadata), atomically.
  * ``dump_on_anomaly()`` — the watchdog's hook (resilience/watchdog.py):
    when a hang / peer-loss escalation or a straggler flag fires, the ring
    dumps automatically and a ``{"event": "trace_dump"}`` row lands in
    metrics.jsonl, so the post-mortem starts with "what was each thread
    doing", not with reproducing the hang. Optionally brackets an
    on-demand ``jax.profiler`` window (utils/profiling.trace_window) for
    device-side visibility too.

Spans may carry a goodput ``category`` (telemetry/goodput.py): the span's
duration is charged to that category on exit, so ONE instrumentation site
feeds both the flight recorder and the goodput accounting. Nested
categorized spans charge only the outermost one (per thread) — an
``eval.batch`` inside an ``eval.round`` must not double-count.

Span names are REGISTERED in :data:`SPAN_CATALOG` — the same drift
contract as ``utils.metrics.EVENT_SCHEMAS``: the registry-drift lint rule
(analysis/rules/registry_drift.py) resolves every ``span("<name>")``
literal against the catalog, and unknown names warn once at runtime
(observability must never kill a run). docs/observability.md is the
operator-facing catalog.
"""
from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

log = logging.getLogger(__name__)

#: bump when the trace.json event shape changes (consumers key on it via
#: the ``trace_dump`` metrics row and the file's otherData block)
SPAN_SCHEMA_VERSION = 9  # 9: + route.attempt/route.health (serving
#                              fleet front door, round 19)
#                          8: + plan.predict/plan.drift_check (what-if
#                              performance planner, round 17)
#                          7: + reshard.* family (elastic mesh
#                              shrink/grow transition, round 16)
#                          6: + comm.probe; comm.bucket / zero1.gather
#                              gain a bucket-index arg so the merged
#                              timeline / comm report can join spans to
#                              the plan (performance observability,
#                              round 14)
#                          5: + serve.variant_build; comm.bucket /
#                              zero1.gather gain a wire_bytes arg
#                              (low-precision hot paths, round 12)
#                          4: + checkpoint.shard/checkpoint.finalize/
#                              zero1.gather (ZeRO-1 sharded update +
#                              per-host sharded checkpoints, round 11)
#                          3: + checkpoint.snapshot/checkpoint.writer/
#                              comm.bucket (zero-stall step loop, round 10)

#: every span name the framework emits — register HERE first (the
#: registry-drift rule rejects unregistered ``span("...")`` literals, the
#: runtime warns once). Value = one-line description for the docs.
SPAN_CATALOG = {
    # input pipeline (data/device_prefetch.py, data/imagenet.py)
    "input.decode": "one image decoded + cropped (decode worker thread)",
    "input.stack": "K host batches drawn + np.stack'ed (stacker thread)",
    "input.echo": "one source batch absorbed into the decoded-sample echo "
                  "cache (data/echo.py; emission busy time rides the "
                  "'echo' stage counter)",
    "input.stage": "host batch packed/staged by the put path (staging "
                   "thread; CoalescedStager pack + issue)",
    "input.transfer": "wait for the previous batch's H2D transfer to "
                      "complete (staging thread)",
    "input.wait": "train loop blocked waiting for the next device batch "
                  "(goodput: input_wait)",
    # train loop (train/loop.py)
    "train.step": "one optimizer-step (or fused K-step) dispatch",
    "eval.round": "one full evaluation round (goodput: eval)",
    "eval.batch": "one eval batch: stage wait + step dispatch",
    # checkpointing (checkpoint/manager.py)
    "checkpoint.save": "save() on the step-loop thread: backpressure + "
                       "host snapshot + handoff (async) or the full "
                       "write (sync) (goodput: checkpoint)",
    "checkpoint.snapshot": "device→host state copy on the step-loop "
                           "thread (async issue, one overlapped D2H "
                           "transfer; the loop-blocking leg of an async "
                           "save)",
    "checkpoint.wait": "step-loop thread blocked on an in-flight async "
                       "save (goodput: checkpoint)",
    "checkpoint.writer": "the dedicated writer thread's whole "
                         "stage→fsync→manifest→commit pass over a host "
                         "snapshot (overlaps compute; accounted in the "
                         "ckpt_async row, NOT goodput checkpoint)",
    "checkpoint.stage": "orbax serialization into the staging dir "
                        "(writer thread when async)",
    "checkpoint.shard": "this host's per-host shard files staged + "
                        "fsynced (sharded layout; writer thread)",
    "checkpoint.finalize": "sharded multi-process finalize: marker-file "
                           "wait for peer shards, then manifest + "
                           "commit rename (chief writer) or the wait "
                           "for the chief's commit (peers)",
    "checkpoint.fsync": "manifest write + fsync",
    "checkpoint.commit": "atomic rename + parent-dir fsync",
    "restore": "checkpoint restore into the live state (goodput: restart "
               "when on the NaN-rollback path)",
    # gradient-communication overlap (parallel/overlap.py)
    "comm.bucket": "one planned gradient-exchange bucket (recorded at "
                   "step TRACE time with bytes/leaves args — the bucket "
                   "plan, not a per-step event)",
    "zero1.gather": "one planned ZeRO-1 param-update all-gather bucket "
                    "(trace-time, like comm.bucket — the gather plan)",
    "comm.probe": "one planned exchange bucket's collective timed "
                  "STANDALONE on the live mesh (parallel/overlap."
                  "probe_comm_plan; bucket/bytes/wire_bytes args — the "
                  "runtime leg the comm_timing row and main.py "
                  "comm-report attribute bandwidth from)",
    # serving (serve/server.py, serve/swap.py)
    "serve.batch": "one bucket dispatch: stage + AOT predict + resolve",
    "serve.swap_restore": "off-path host restore of a newer checkpoint",
    "serve.swap_apply": "atomic param swap at a batch boundary",
    "serve.variant_build": "one serving precision variant's weight copy "
                           "cast from the f32 masters (startup and every "
                           "hot swap; docs/precision.md)",
    # serving fleet front door (serve/router.py, docs/serving.md)
    "route.attempt": "one request attempt forwarded to a replica "
                     "(router worker thread: send → response/failure; "
                     "replica/attempt args — hedges and retries are "
                     "extra route.attempt spans for the same request)",
    "route.health": "one health-scan pass over the fleet (router health "
                    "thread: heartbeat ages + telemetry tails + canary "
                    "controller turn)",
    # elastic mesh generation transition (resilience/elastic.py;
    # goodput: reshard for every leg — the whole transition is
    # non-compute wall time)
    "reshard.barrier": "file-based join barrier: post membership, wait "
                       "for the settle window + the chief candidate's "
                       "commit record (no collectives — peers may be "
                       "dead)",
    "reshard.teardown": "dead-mesh teardown: abandon the blocking "
                        "distributed-client shutdown in a daemon thread, "
                        "reset jax's process-global distributed state, "
                        "clear backends + caches",
    "reshard.init": "jax.distributed re-initialize over the survivors at "
                    "the new generation's epoch-suffixed coordinator",
    "reshard.restore": "last committed checkpoint restored into the new "
                       "topology (sharded M≠N assemble path when the "
                       "layout is sharded)",
    "reshard.rebuild": "Trainer/mesh/sharding re-elaboration + input "
                       "source rebuild for the new generation",
    # what-if performance planner (telemetry/planner.py)
    "plan.predict": "one layout × knob candidate costed by the analytic "
                    "model (preset/layout args; main.py plan and the "
                    "plan-drift gate phase)",
    "plan.drift_check": "one predicted-vs-measured comparison by the "
                        "drift sentinel (train/hooks.py PlanDriftHook "
                        "cadence firing)",
}

# unknown span names already warned about (warn once, like write_event)
_UNKNOWN_SPANS_WARNED: set = set()


class _NoopSpan:
    """Shared do-nothing span for a disabled recorder."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _Span:
    __slots__ = ("_rec", "name", "category", "args", "_t0", "_counted")

    def __init__(self, rec: "FlightRecorder", name: str,
                 category: Optional[str], args: Optional[dict]):
        self._rec = rec
        self.name = name
        self.category = category
        self.args = args

    def __enter__(self):
        self._counted = False
        if self.category is not None:
            # outermost-categorized-span guard (see module docstring):
            # only spans that carried a category touch the depth counter
            local = self._rec._local
            depth = getattr(local, "cat_depth", 0)
            local.cat_depth = depth + 1
            self._counted = True
            if depth:
                self.category = None
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        rec = self._rec
        dur = t1 - self._t0
        tid = threading.get_ident()
        if tid not in rec._thread_names:
            rec._thread_names[tid] = threading.current_thread().name
        with rec._lock:
            rec._events.append((self.name, tid, self._t0, dur, self.args))
        if self._counted:
            rec._local.cat_depth -= 1
        if self.category is not None:
            from .goodput import goodput
            goodput.add(self.category, dur)
        return False


class FlightRecorder:
    """The process-global bounded span ring + dump machinery.

    ``configure()`` is called once per entry point (main.py) with the run's
    dump directory and (chief-only) metrics writer; until then spans still
    record — only automatic dumps need the configuration.
    """

    def __init__(self, ring: int = 65536, enabled: bool = True):
        self._lock = threading.Lock()
        self._events: collections.deque = collections.deque(maxlen=ring)
        self._thread_names: Dict[int, str] = {}
        self._local = threading.local()
        self._enabled = enabled
        self._epoch_perf = time.perf_counter()
        self._epoch_wall = time.time()
        self._dump_dir: Optional[str] = None
        self._writer = None
        self._process_index = 0
        self._profile_on_anomaly = False
        self._profile_secs = 5.0
        self._profiled = False

    # -- configuration ------------------------------------------------------
    def configure(self, dump_dir: Optional[str] = None, writer=None,
                  ring: Optional[int] = None,
                  enabled: Optional[bool] = None,
                  process_index: Optional[int] = None,
                  profile_on_anomaly: Optional[bool] = None,
                  profile_secs: Optional[float] = None) -> None:
        if ring is not None and ring != self._events.maxlen:
            with self._lock:
                self._events = collections.deque(self._events, maxlen=ring)
        if enabled is not None:
            self._enabled = enabled
        if dump_dir is not None:
            self._dump_dir = dump_dir
        if writer is not None:
            self._writer = writer
        if process_index is not None:
            self._process_index = process_index
        if profile_on_anomaly is not None:
            self._profile_on_anomaly = profile_on_anomaly
        if profile_secs is not None:
            self._profile_secs = profile_secs

    @property
    def enabled(self) -> bool:
        return self._enabled

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        return len(self._events)

    # -- recording ----------------------------------------------------------
    def span(self, name: str, category: Optional[str] = None, **args):
        """Context manager timing one event. ``category`` charges the
        duration to the goodput meter (outermost categorized span per
        thread only); ``**args`` ride into the trace event (keep them off
        hot paths — the dict allocation is the cost)."""
        if not self._enabled:
            return _NOOP
        if name not in SPAN_CATALOG and name not in _UNKNOWN_SPANS_WARNED:
            _UNKNOWN_SPANS_WARNED.add(name)
            log.warning(
                "span %r is not declared in telemetry.tracer.SPAN_CATALOG "
                "— register it (the registry-drift lint rejects "
                "undeclared literals)", name)
        return _Span(self, name, category, args or None)

    # -- dumping ------------------------------------------------------------
    def trace_events(self) -> list:
        """The ring as Chrome-trace event dicts (ts/dur in microseconds,
        relative to the recorder epoch)."""
        with self._lock:
            snap = list(self._events)
        names = dict(self._thread_names)
        pid = os.getpid()
        events = [
            {"name": f"thread: {tname}", "ph": "M", "pid": pid, "tid": tid,
             "ts": 0, "cat": "__metadata", "args": {"name": tname}}
            for tid, tname in sorted(names.items())]
        # Perfetto also honors the canonical thread_name metadata record
        events += [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "ts": 0, "args": {"name": tname}}
            for tid, tname in sorted(names.items())]
        for name, tid, t0, dur, args in snap:
            ev = {"name": name, "ph": "X", "pid": pid, "tid": tid,
                  "ts": round((t0 - self._epoch_perf) * 1e6, 3),
                  "dur": round(dur * 1e6, 3), "cat": "span"}
            if args:
                ev["args"] = {k: _jsonable(v) for k, v in args.items()}
            events.append(ev)
        return events

    def default_dump_path(self) -> Optional[str]:
        if self._dump_dir is None:
            return None
        name = "trace.json" if self._process_index == 0 \
            else f"trace.proc{self._process_index}.json"
        return os.path.join(self._dump_dir, name)

    def dump(self, path: Optional[str] = None,
             reason: str = "on_demand") -> Optional[str]:
        """Write the ring as ``trace.json`` (atomic tmp+rename). Returns
        the path written, or None when no path is known. Never raises —
        the callers are crash/teardown paths."""
        try:
            path = path or self.default_dump_path()
            if path is None:
                return None
            events = self.trace_events()
            doc = {
                "traceEvents": events,
                "displayTimeUnit": "ms",
                "otherData": {
                    "span_schema_version": SPAN_SCHEMA_VERSION,
                    "reason": reason,
                    "process_index": self._process_index,
                    "pid": os.getpid(),
                    "epoch_wall_time": self._epoch_wall,
                    "ring_capacity": self._events.maxlen,
                },
            }
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
            log.info("flight recorder: %d span(s) dumped to %s (%s)",
                     sum(1 for e in events if e.get("ph") == "X"), path,
                     reason)
            return path
        except Exception:  # a failed dump must not worsen the teardown
            log.exception("flight recorder dump failed")
            return None

    def dump_on_anomaly(self, kind: str, detail: str = "") -> Optional[str]:
        """The watchdog / fatal-exit hook: dump the ring, record a
        ``trace_dump`` metrics row (chief), optionally bracket a
        ``jax.profiler`` window (telemetry.profile_on_anomaly — once per
        process: a flapping straggler must not profile in a loop)."""
        path = self.dump(reason=kind)
        if self._writer is not None:
            try:
                self._writer.write_event("trace_dump", {
                    "reason": kind, "detail": detail,
                    "path": path or "",
                    "spans": len(self._events),
                    "span_schema_version": SPAN_SCHEMA_VERSION})
                self._writer.flush()
            except Exception:  # pragma: no cover - observability best effort
                log.exception("trace_dump metrics row failed")
        if self._profile_on_anomaly and not self._profiled \
                and self._dump_dir is not None:
            self._profiled = True
            try:
                from ..utils.profiling import trace_window
                trace_window(os.path.join(self._dump_dir, "profile"),
                             self._profile_secs)
            except Exception:  # pragma: no cover - profiler best effort
                log.exception("anomaly-triggered jax.profiler window failed")
        return path


def _jsonable(v: Any):
    return v if isinstance(v, (str, int, float, bool, type(None))) else str(v)


#: the process-global recorder every instrumentation site uses
recorder = FlightRecorder()

#: ``from ..telemetry import span`` — the one spelling the lint rule knows
span = recorder.span
