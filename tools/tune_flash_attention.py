"""Autotune the flash-attention Pallas tile sizes on real TPU.

Measures fwd+bwd (grad) wall time over (block_q, block_k) ∈ {128,256,512}²
for T ∈ {1024, 2048, 4096, 8192} × head dim ∈ {64, 128} (bf16, causal), plus
the XLA dense and blockwise baselines at each point — the evidence for
ops/pallas/flash_attention._BLOCK_TABLES and for the dense→flash ``auto``
crossover in models/transformer.py.

    python tools/tune_flash_attention.py [--out docs/flash_tune_r3.json]
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

BLOCKS = (128, 256, 512)
SEQS = (1024, 2048, 4096, 8192)
HEAD_DIMS = (64, 128)


def grad_time(attn_fn, q, k, v, iters=8, reps=3):
    """One shared harness with the bench (bench.attention_grad_ms) so the
    tuner's numbers and the bench's stay methodologically identical."""
    from bench import attention_grad_ms
    return attention_grad_ms(attn_fn, q, k, v, iters, reps)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/flash_tune_r3.json")
    ap.add_argument("--seqs", default=",".join(map(str, SEQS)))
    ap.add_argument("--dims", default=",".join(map(str, HEAD_DIMS)))
    ap.add_argument("--heads_budget", type=int, default=8 * 64 * 4096,
                    help="keep B*H*T*D work roughly constant across points")
    args = ap.parse_args()
    from distributed_resnet_tensorflow_tpu.ops.attention import (
        attention, blockwise_attention)
    from distributed_resnet_tensorflow_tpu.ops.pallas import flash_attention

    results = []
    out = {"device": jax.devices()[0].device_kind, "results": results}
    if os.path.exists(args.out):
        with open(args.out) as f:
            prev = json.load(f)
        done = {(r["t"], r["d"]): r for r in prev.get("results", [])}
        # carry EVERY previously-measured point — a --dims/--seqs subset run
        # must extend the evidence file, not clobber it
        results.extend(prev.get("results", []))
    else:
        done = {}
    for t in map(int, args.seqs.split(",")):
        for d in map(int, args.dims.split(",")):
            if (t, d) in done:
                continue
            h = max(1, args.heads_budget // (t * d))
            rng = np.random.RandomState(0)
            q, k, v = (jnp.asarray(
                rng.randn(1, t, h, d).astype(np.float32) * 0.3)
                .astype(jnp.bfloat16) for _ in range(3))
            point = {"t": t, "d": d, "h": h, "blocks": {}}
            for bq, bk in itertools.product(BLOCKS, BLOCKS):
                ms = grad_time(lambda q, k, v, bq=bq, bk=bk: flash_attention(
                    q, k, v, True, False, bq, bk), q, k, v)
                point["blocks"][f"{bq}x{bk}"] = round(ms, 3)
                print(f"T={t} d={d} h={h} block {bq}x{bk}: {ms:.3f} ms",
                      flush=True)
            best = min(point["blocks"], key=point["blocks"].get)
            point["best"] = best
            point["dense_ms"] = round(grad_time(
                lambda q, k, v: attention(q, k, v, causal=True), q, k, v), 3)
            try:
                point["blockwise_ms"] = round(grad_time(
                    lambda q, k, v: blockwise_attention(q, k, v, causal=True),
                    q, k, v), 3)
            except Exception as e:
                point["blockwise_ms"] = f"error: {e}"[:80]
            point["speedup_vs_dense"] = round(
                point["dense_ms"] / point["blocks"][best], 2)
            print(f"T={t} d={d}: best {best} "
                  f"({point['blocks'][best]} ms) vs dense {point['dense_ms']}"
                  f" ms -> {point['speedup_vs_dense']}x", flush=True)
            results.append(point)
            if os.path.dirname(args.out):
                os.makedirs(os.path.dirname(args.out), exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
