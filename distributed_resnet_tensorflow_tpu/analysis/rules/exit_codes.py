"""exit-code-contract: process exit codes come from the declared registry.

Launchers key requeue-vs-fail decisions off exit codes (docs/resilience.md:
0 = done, 75 = resumable/requeue, 1 = real failure). A stray
``sys.exit(3)`` silently breaks that protocol — SLURM would treat a
resumable condition as a hard failure or vice versa. This rule flags any
``sys.exit``/``os._exit`` whose argument is an integer literal not in
``resilience.EXIT_CONTRACT``. Named constants (RESUMABLE_EXIT_CODE,
FAILURE_EXIT_CODE) and computed codes (exit-code pass-through in
launchers) are accepted — the contract is about new literals.
"""
from __future__ import annotations

import ast
from typing import Iterable

from ..report import Finding

RULE_NAME = "exit-code-contract"
DOC = __doc__


def _contract_codes() -> set:
    from ...resilience import EXIT_CONTRACT
    return set(EXIT_CONTRACT)


def _is_exit_call(node: ast.Call) -> bool:
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in ("exit", "_exit"):
        base = fn.value
        return isinstance(base, ast.Name) and base.id in ("sys", "os")
    return False


def check(ctx) -> Iterable[Finding]:
    codes = _contract_codes()
    for sf in ctx.all_python():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.Call) and _is_exit_call(node)):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, int) and \
                    not isinstance(arg.value, bool) and \
                    arg.value not in codes:
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    f"exit code {arg.value} is not in the declared "
                    f"contract {sorted(codes)} (resilience.EXIT_CONTRACT) "
                    "— launchers cannot classify it; declare it or reuse "
                    "an existing code")
