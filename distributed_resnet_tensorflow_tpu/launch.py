"""Multi-process launcher/supervisor — successor of the reference's launcher
tree.

The reference bootstrapped clusters with ~440 lines of bash deriving ps/worker
host:port lists from SLURM and synthesizing per-node scripts
(reference scripts/run_dist_tf_daint.sh:30-206, SURVEY.md §2.18). In the SPMD
world a launcher only needs to start N identical processes with
(coordinator, process_id) — everything else is the same program.

Since the watchdog PR this is a real SUPERVISOR, not a serial waiter: it
polls all children, and when any child exits BADLY (nonzero other than the
resumable 75, or by signal) while siblings are still running it gives the
survivors ``child_grace_secs`` to finish on their own (the in-process
watchdog, resilience/watchdog.py, normally gets them out with exit 75 well
within that), then escalates SIGTERM → SIGKILL so one dead worker can
never wedge the whole allocation until the wall clock. A CLEAN or
RESUMABLE first exit (0 or 75) arms only a much longer backstop grace —
siblings legitimately finish or drain their preemption checkpoint at
different speeds, and killing them would tear the very save the grace
exists to protect.

Exit-code aggregation (docs/resilience.md):
  * any child's real failure (positive code other than 75) wins — a broken
    job must never be masked as "preempted" and requeued forever;
  * otherwise 75 if any child exited resumable OR died by signal (host
    loss / OOM-kill — the requeue-and-resume shape) OR had to be torn down
    by the supervisor;
  * 0 only when every child finished cleanly.

Modes:
  * ``--num_processes N`` local fan-out — the successor of the reference's
    1ps+2wk localhost smoke cluster (reference scripts/submit_mac_dist.sh,
    run_dist_tf_local.sh: bs=10, 100 steps on CPU). Each child gets a fake
    single-CPU-device platform unless --devices_per_process says otherwise.
  * under SLURM, don't use this at all: ``srun python -m
    distributed_resnet_tensorflow_tpu.main …`` — parallel/distributed.py
    reads SLURM_NTASKS/SLURM_PROCID/nodelist itself (scripts/submit_tpu_slurm.sh).
  * on Cloud TPU pods, run main.py on every TPU VM worker;
    jax.distributed.initialize autodetects the pod topology (no args needed).

Usage:
    python -m distributed_resnet_tensorflow_tpu.launch --num_processes 2 -- \
        --preset smoke --set train.train_steps=20
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

from distributed_resnet_tensorflow_tpu.resilience.preemption import (
    INTERRUPT_EXIT_CODE, RESUMABLE_EXIT_CODE)

log = logging.getLogger(__name__)

#: once any child has exited BADLY (non-resumable nonzero / signal), how
#: long the siblings get before SIGTERM
DEFAULT_CHILD_GRACE_SECS = 30.0
#: after SIGTERM, how long before SIGKILL
TERM_TO_KILL_SECS = 10.0
#: grace multiplier/floor when the first exit was CLEAN (code 0): a slower
#: sibling draining a long final checkpoint is the normal end of a healthy
#: run, not a failure — tearing it down would turn success into a requeue.
#: A sibling that instead wedges after a clean exit is covered by its own
#: in-process watchdog (hang detection → exit 75), so this long stop is a
#: backstop, not the primary detector.
CLEAN_EXIT_GRACE_FLOOR_SECS = 300.0
CLEAN_EXIT_GRACE_SCALE = 10.0


def _spawn_one(pid: int, num_processes: int, main_args: List[str],
               devices_per_process: int, port: int,
               rejoin: bool = False) -> subprocess.Popen:
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        virtual_cpu_env)
    env = virtual_cpu_env(devices_per_process)
    if rejoin:
        # a replacement worker must not re-arm the fault that killed its
        # predecessor, and enters through the elastic join barrier
        # (resilience/elastic.py) instead of the dead generation's
        # coordinator — main.py keys off DRT_ELASTIC_REJOIN
        for key in [k for k in env if k.startswith("DRT_FAULT_")]:
            env.pop(key)
        env["DRT_ELASTIC_REJOIN"] = "1"
    cmd = [sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
           *main_args,
           "--set", f"mesh.coordinator_address=127.0.0.1:{port}",
           "--set", f"mesh.num_processes={num_processes}",
           "--set", f"mesh.process_id={pid}"]
    # chief inherits stdout/stderr; others keep their own log files —
    # per-process logs like the reference's worker.$JOBID.$host.log
    # (reference run_dist_train_eval_daint.sh:161,188)
    if pid == 0:
        out = None
    else:
        os.makedirs("/tmp/drt_launch", exist_ok=True)
        out = open(f"/tmp/drt_launch/proc{pid}.log",
                   "a" if rejoin else "w")
    return subprocess.Popen(cmd, env=env, stdout=out, stderr=out)


def _spawn(num_processes: int, main_args: List[str],
           devices_per_process: int, port: int) -> List[subprocess.Popen]:
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        existing_device_count)

    if not devices_per_process:
        devices_per_process = existing_device_count(
            os.environ.get("XLA_FLAGS", "")) or 1
    return [_spawn_one(pid, num_processes, main_args, devices_per_process,
                       port)
            for pid in range(num_processes)]


def _signal_all(procs: List[subprocess.Popen], sig: int,
                skip_done: bool = True) -> None:
    for p in procs:
        if skip_done and p.poll() is not None:
            continue
        try:
            p.send_signal(sig)
        except ProcessLookupError:
            pass


def terminate_child(proc: subprocess.Popen,
                    grace_secs: float = TERM_TO_KILL_SECS,
                    kill_after: float = TERM_TO_KILL_SECS) -> int:
    """Escalation ladder for ONE child: SIGTERM → wait ``grace_secs`` →
    SIGKILL → wait ``kill_after`` → reap. Returns the exit code (negative
    = signal death). Shared by the serving fleet supervisor
    (serve/fleet.py replica replace) so every child teardown in the tree
    follows the same term-then-kill contract as the training launcher."""
    if proc.poll() is None:
        try:
            proc.terminate()
        except ProcessLookupError:
            pass
        try:
            proc.wait(timeout=grace_secs)
        except subprocess.TimeoutExpired:
            try:
                proc.kill()
            except ProcessLookupError:
                pass
            try:
                proc.wait(timeout=kill_after)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass
    return proc.returncode if proc.returncode is not None else -signal.SIGKILL


def _aggregate_rc(codes: List[int], forced: set) -> int:
    """Exit-code policy (module docstring): real failure > resumable > 0.
    Signal deaths (negative codes) of children the supervisor did NOT kill
    are host-loss-shaped → resumable. Children the supervisor tore down
    usually carry no information beyond "the run needed teardown" (signal
    death or the graceful 75) — EXCEPT a positive, non-resumable code: a
    forced child that still exited with its own failure code crashed for
    real (racing the teardown), and masking that as 75 would requeue a
    deterministically-broken job until MAX_REQUEUES."""
    rc = 0
    tore_down = False
    for i, code in enumerate(codes):
        if i in forced:
            tore_down = tore_down or code != 0
            if code <= 0 or code == RESUMABLE_EXIT_CODE:
                continue
            # fall through: the child's own real failure still wins
        if code == 0:
            continue
        if code < 0 or code == RESUMABLE_EXIT_CODE:
            if rc == 0:
                rc = RESUMABLE_EXIT_CODE
        else:
            rc = code  # real failure: wins over resumable, first one kept
            break
    if rc == 0 and tore_down:
        # everyone we left alone succeeded but some children had to be
        # killed — the run did not complete; requeue-shaped
        rc = RESUMABLE_EXIT_CODE
    return rc


def launch_local(num_processes: int, main_args: List[str],
                 devices_per_process: int = 0, port: int = 8476,
                 child_grace_secs: float = DEFAULT_CHILD_GRACE_SECS,
                 poll_secs: float = 0.2,
                 procs_out: Optional[list] = None,
                 elastic: bool = False,
                 max_respawns: int = 2,
                 respawn_delay_secs: float = 2.0) -> int:
    """Spawn N copies of main.py on localhost over the loopback coordinator
    and supervise them to completion (see module docstring for the exit-code
    aggregation). ``devices_per_process=0`` (default) honors a device count
    the user already exported via XLA_FLAGS, falling back to 1.

    ``procs_out``: optional list the spawned Popen objects are appended to —
    the fault-injection tests need the children's pids to kill one
    (tests/test_resilience.py kill-and-detect).

    ``elastic``: respawn a child that died respawnable (signal death or
    exit 75) into its ORIGINAL slot with ``DRT_ELASTIC_REJOIN`` set, up to
    ``max_respawns`` times per slot — the replacement joins the live
    fleet's elastic barrier and the mesh grows back
    (resilience/elastic.py). Requires ``resilience.elastic.enabled=on`` in
    ``main_args``; respawnable deaths do NOT arm the bad-exit teardown
    countdown in this mode (the survivors are busy resharding, not
    wedged). A slot's FINAL incarnation decides its exit code."""
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        existing_device_count)
    if not devices_per_process:
        devices_per_process = existing_device_count(
            os.environ.get("XLA_FLAGS", "")) or 1
    procs = _spawn(num_processes, main_args, devices_per_process, port)
    if procs_out is not None:
        procs_out.extend(procs)

    # forward SIGTERM (SLURM grace-period kill, kill.sh) to every child so
    # each commits its preemption checkpoint and exits resumable; the
    # supervisor then reports the children's own exit code
    def forward_term(signum, frame):
        _signal_all(procs, signal.SIGTERM)

    try:
        prev_term = signal.signal(signal.SIGTERM, forward_term)
    except ValueError:  # not the main thread (embedded use) — no forwarding
        prev_term = None

    clean_grace_secs = max(CLEAN_EXIT_GRACE_SCALE * child_grace_secs,
                           CLEAN_EXIT_GRACE_FLOOR_SECS)
    forced: set = set()
    first_exit_at: Optional[float] = None
    first_bad_exit_at: Optional[float] = None
    termed_at: Optional[float] = None
    respawns = [0] * num_processes
    pending_respawn: dict = {}  # slot -> monotonic due time
    try:
        while True:
            codes = [p.poll() for p in procs]
            now = time.monotonic()
            if elastic and termed_at is None:
                any_clean = any(c == 0 for c in codes)
                for i, c in enumerate(codes):
                    if c is None or i in pending_respawn or i in forced:
                        continue
                    if any_clean:
                        continue  # the run is finishing — no new workers
                    if (c < 0 or c == RESUMABLE_EXIT_CODE) and \
                            respawns[i] < max_respawns:
                        respawns[i] += 1
                        pending_respawn[i] = now + respawn_delay_secs
                        log.warning(
                            "elastic: child %d died respawnable (code %d); "
                            "respawning as a rejoiner in %.0fs "
                            "(attempt %d/%d)", i, c, respawn_delay_secs,
                            respawns[i], max_respawns)
                for i, due in list(pending_respawn.items()):
                    if now >= due:
                        procs[i] = _spawn_one(
                            i, num_processes, main_args,
                            devices_per_process, port, rejoin=True)
                        if procs_out is not None:
                            procs_out.append(procs[i])
                        del pending_respawn[i]
                # slots awaiting (or fresh from) respawn are not exits for
                # the teardown timers; with everyone live again the
                # countdown state resets — the fleet recovered
                codes = [None if i in pending_respawn else p.poll()
                         for i, p in enumerate(procs)]
                if all(c is None for c in codes):
                    first_exit_at = None
                    first_bad_exit_at = None
            live = [i for i, c in enumerate(codes) if c is None]
            if not live:
                break
            if first_exit_at is None and any(c is not None for c in codes):
                first_exit_at = now
            # a deliberate resumable exit (75) is not a failure: during a
            # fleet-wide preemption children exit 75 at different speeds,
            # and the short countdown would SIGKILL a slow sibling mid-
            # preemption-checkpoint — the very save the grace protects
            if first_bad_exit_at is None and \
                    any(c is not None and c != 0 and
                        c != RESUMABLE_EXIT_CODE for c in codes):
                first_bad_exit_at = now
                exited = {i: c for i, c in enumerate(codes) if c is not None}
                log.warning(
                    "child exit(s) %s with %d sibling(s) still running; "
                    "giving them %.0fs before teardown", exited,
                    len(live), child_grace_secs)
            # the short countdown arms only on a BAD exit (nonzero
            # non-resumable, or signal death); after clean/resumable-only
            # exits the survivors get clean_grace_secs (finishing at
            # different speeds is a healthy run's normal shape)
            if first_bad_exit_at is not None:
                teardown_due = now - first_bad_exit_at >= child_grace_secs
            else:
                teardown_due = first_exit_at is not None and \
                    now - first_exit_at >= clean_grace_secs
            if teardown_due and termed_at is None:
                log.warning("teardown: SIGTERM to %d straggling child(ren) "
                            "%.0fs after the first exit", len(live),
                            now - first_exit_at)
                forced.update(live)
                _signal_all(procs, signal.SIGTERM)
                termed_at = now
            if termed_at is not None and now - termed_at >= TERM_TO_KILL_SECS:
                log.error("teardown: SIGKILL to %d child(ren) that ignored "
                          "SIGTERM", len(live))
                forced.update(live)
                _signal_all(procs, signal.SIGKILL)
                termed_at = now  # keep kicking every TERM_TO_KILL_SECS
            time.sleep(poll_secs)
        rc = _aggregate_rc([p.returncode for p in procs], forced)
    except KeyboardInterrupt:  # kill.sh parity (reference scripts/kill.sh)
        _signal_all(procs, signal.SIGTERM, skip_done=False)
        rc = INTERRUPT_EXIT_CODE
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
        for p in procs:  # reap everything; no zombies left to SLURM
            try:
                p.wait(timeout=TERM_TO_KILL_SECS)
            except subprocess.TimeoutExpired:  # pragma: no cover
                p.kill()
                p.wait()
    if rc == RESUMABLE_EXIT_CODE:
        log.warning("children stopped resumable; exit code %d marks the run "
                    "resumable — relaunch with the same config to resume",
                    RESUMABLE_EXIT_CODE)
    return rc


def _apply_auto_layout(main_args: List[str], num_processes: int,
                       devices_per_process: int) -> List[str]:
    """--auto-layout: resolve the preset the children will run, ask the
    planner for the fastest predicted layout at this world size, and
    prepend the matching ``--set mesh.*`` overrides. Prepend, not
    append: config overrides apply in order, so a user's explicit
    ``--set mesh.*`` later in main_args still wins. Planner failures
    (no committed schedules for the preset, import error on an exotic
    install) log and fall through to the preset's own mesh — the
    launcher must never refuse to launch over an advisory."""
    preset = "cifar10_resnet50"  # utils.config.parse_args default
    for i, a in enumerate(main_args):
        if a == "--preset" and i + 1 < len(main_args):
            preset = main_args[i + 1]
        elif a.startswith("--preset="):
            preset = a.split("=", 1)[1]
    n_devices = num_processes * devices_per_process
    try:
        from .telemetry.planner import recommend_layout
        rec = recommend_layout(preset, n_devices=n_devices)
    except Exception as e:  # advisory only — never block the launch
        log.warning("--auto-layout: planner failed (%s); launching "
                    "with the preset's own mesh", e)
        return main_args
    if rec is None:
        log.warning("--auto-layout: no committed schedules for preset "
                    "%r (run `main.py check` first); launching with "
                    "the preset's own mesh", preset)
        return main_args
    layout, mesh_cfg = rec
    overrides = []
    for axis in ("data", "fsdp", "tensor", "pipeline", "sequence",
                 "expert"):
        overrides += ["--set", f"mesh.{axis}={getattr(mesh_cfg, axis)}"]
    log.info("--auto-layout: planner recommends %s for %s @ %d "
             "device(s): %s", layout, preset, n_devices,
             " ".join(overrides[1::2]))
    return overrides + list(main_args)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="local multi-process SPMD launcher/supervisor")
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--devices_per_process", type=int, default=0,
                    help="0 = inherit XLA_FLAGS device count, else 1")
    ap.add_argument("--port", type=int, default=8476)
    ap.add_argument("--child_grace_secs", type=float,
                    default=DEFAULT_CHILD_GRACE_SECS,
                    help="seconds siblings get to exit on their own after "
                         "the first BAD (non-resumable nonzero / signal) "
                         "child exit, before SIGTERM/SIGKILL; clean/75 "
                         "exits arm a 10x/300s-floor backstop instead")
    ap.add_argument("--elastic", action="store_true",
                    help="respawn a child that died respawnable (signal "
                         "or exit 75) into its slot as an elastic "
                         "rejoiner (DRT_ELASTIC_REJOIN); pair with "
                         "--set resilience.elastic.enabled=on")
    ap.add_argument("--max_respawns", type=int, default=2,
                    help="per-slot respawn budget in --elastic mode")
    ap.add_argument("--respawn_delay_secs", type=float, default=2.0,
                    help="delay before an elastic respawn (lets the "
                         "survivors reach the join barrier first)")
    ap.add_argument("--auto-layout", action="store_true",
                    help="ask the what-if planner (telemetry/planner."
                         "recommend_layout, docs/planner.md) for the "
                         "fastest predicted mesh layout at this world "
                         "size and inject the matching --set mesh.* "
                         "overrides BEFORE the user's own args (an "
                         "explicit --set mesh.* still wins)")
    ap.add_argument("main_args", nargs=argparse.REMAINDER,
                    help="args after -- go to main.py")
    ns = ap.parse_args(argv)
    main_args = ns.main_args
    if main_args and main_args[0] == "--":
        main_args = main_args[1:]
    if ns.auto_layout:
        main_args = _apply_auto_layout(
            main_args, ns.num_processes, ns.devices_per_process or 1)
    sys.exit(launch_local(ns.num_processes, main_args,
                          ns.devices_per_process, ns.port,
                          child_grace_secs=ns.child_grace_secs,
                          elastic=ns.elastic,
                          max_respawns=ns.max_respawns,
                          respawn_delay_secs=ns.respawn_delay_secs))


if __name__ == "__main__":
    main()
