"""Mixture-of-Experts MLP (Switch-style top-1 routing) — the consumer of the
``expert`` mesh axis.

The reference is a dense-only trainer (SURVEY.md §2.10); this completes the
6-axis mesh so every axis has a model consumer. Design (Switch Transformer
recipe, scoped to what the ViT family needs):

  * E expert MLPs with stacked parameters (E, D, F)/(E, F, D), sharded over
    the ``expert`` axis by parallel/sharding.py's rule — each device group
    holds E/expert_axis experts (and their optimizer moments).
  * Top-1 routing with probability gating and a fixed per-expert capacity
    ``ceil(tokens/E · capacity_factor)``; over-capacity tokens fall through
    on the residual path (standard Switch behavior).
  * Dispatch/combine are one-hot einsums — GSPMD partitions them over the
    sharded expert dimension and inserts the token exchange collectives.
    This is the sharding-first formulation (no hand-written all-to-all);
    optimal a2a scheduling is left to XLA.
  * The Switch load-balancing auxiliary loss (E · Σ_e fraction_e · prob_e)
    is sown into the ``losses`` collection; the train step adds every sown
    loss scaled by ``model.moe_aux_weight`` (train/loop.py).
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class SwitchMlp(nn.Module):
    """Drop-in replacement for the EncoderBlock MLP: LN'd input in,
    residual-branch output out. Shapes: (B, T, D) → (B, T, D)."""

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Any = None

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        e = self.num_experts
        f = self.mlp_ratio * d
        n_tokens = b * t
        import math
        capacity = max(1, math.ceil((n_tokens / e) * self.capacity_factor))

        vs = jax.nn.initializers.variance_scaling
        w1 = self.param("w1", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, d, f), jnp.float32)
        # "bias" in the name keeps these out of weight decay / LARS trust
        # scaling (the optimizer masks exclude *bias* leaves by path, since
        # expert-stacked biases are 2-D and defeat the ndim heuristic)
        b1 = self.param("bias1", nn.initializers.zeros, (e, f), jnp.float32)
        w2 = self.param("w2", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, f, d), jnp.float32)
        b2 = self.param("bias2", nn.initializers.zeros, (e, d), jnp.float32)

        # --- router (replicated, fp32 for a stable softmax) ---------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32))                       # (B, T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        flat_probs = probs.reshape(n_tokens, e)
        expert_idx = jnp.argmax(flat_probs, axis=-1)     # (N,)
        gate = jnp.max(flat_probs, axis=-1)              # (N,)

        # Switch aux loss: E * Σ_e (fraction of tokens routed to e) · (mean
        # router prob of e) — pushes the router toward uniform utilization
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        fraction = onehot.mean(axis=0)
        mean_prob = flat_probs.mean(axis=0)
        self.sow("losses", "moe_aux", e * jnp.sum(fraction * mean_prob))

        # --- capacity assignment ------------------------------------------
        # position of each token within its expert's queue; >= capacity drops
        pos_in_expert = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # (N, E)
        pos = jnp.sum(pos_in_expert, axis=-1).astype(jnp.int32)      # (N,)
        keep = pos < capacity
        gate = gate * keep.astype(jnp.float32)

        # dispatch: (N, E, C) one-hot — token n feeds slot (expert, pos)
        dispatch = (onehot[:, :, None]
                    * jax.nn.one_hot(pos, capacity, dtype=jnp.float32)[:, None, :]
                    * keep[:, None, None].astype(jnp.float32))
        combine = dispatch * gate[:, None, None]

        flat_x = x.reshape(n_tokens, d)
        # expert inputs (E, C, D): GSPMD shards the E dim over `expert`
        ein = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype),
                         flat_x.astype(self.dtype))
        ein = self._constrain_e(ein)
        h = jnp.einsum("ecd,edf->ecf", ein, w1.astype(self.dtype)) \
            + b1[:, None, :].astype(self.dtype)
        h = nn.gelu(h)
        eout = jnp.einsum("ecf,efd->ecd", h, w2.astype(self.dtype)) \
            + b2[:, None, :].astype(self.dtype)
        eout = self._constrain_e(eout)
        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), eout)
        return out.reshape(b, t, d)

    def _constrain_e(self, arr):
        """Pin the expert dim to the `expert` axis so expert compute stays
        where the weights live."""
        mesh = self.mesh
        if mesh is None or mesh.shape.get("expert", 1) <= 1:
            return arr
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, P("expert", None, None)))
