"""Multi-host bootstrap.

Replaces the reference's cluster bring-up — ``tf.train.ClusterSpec`` +
``tf.train.Server`` grpc bootstrap (reference resnet_cifar_main.py:364-380)
and Horovod's ``hvd.init()`` MPI bootstrap (reference
resnet_cifar_main_horovod.py:342) — with ``jax.distributed.initialize`` over
DCN: one process per TPU host, every process runs the same SPMD program.

Topology can come from explicit config, from SLURM env vars (the reference's
launchers derived ps/worker host lists from ``scontrol show hostnames``,
reference scripts/run_dist_tf_daint.sh:30-76 — here SLURM integration is just
reading env), or from TPU-pod metadata (jax autodetects when args are None).
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Optional

import jax

log = logging.getLogger(__name__)


def initialize_from_config(mesh_cfg) -> None:
    """Initialize the distributed runtime if the config asks for >1 process."""
    if mesh_cfg.num_processes <= 1 and not mesh_cfg.coordinator_address:
        return
    initialize(
        coordinator_address=mesh_cfg.coordinator_address or None,
        num_processes=mesh_cfg.num_processes or None,
        process_id=mesh_cfg.process_id,
    )


def _enable_cpu_collectives() -> None:
    """Pick a real cross-process collectives backend for the CPU platform.

    jaxlib's default CPU collectives are single-process only ("Multiprocess
    computations aren't implemented on the CPU backend"); gloo is the
    multi-process implementation. Setting the env var is NOT enough — this
    environment's sitecustomize drives jax.config at interpreter start, so
    the flag must be flipped through jax.config before the backend
    initializes. No-op on non-CPU platforms and when the operator already
    chose an implementation."""
    try:
        # NOTE the asymmetric accessors: jax 0.4.37 exposes plain flags via
        # config.read() only, context-managed ones via attribute only
        if jax.config.read("jax_cpu_collectives_implementation") != "none":
            return  # operator/site already chose one
        platforms = jax.config.jax_platforms or ""
        if platforms.split(",")[0].strip() != "cpu":
            return
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        log.info("CPU platform multi-process: collectives set to gloo")
    except Exception as e:  # unknown option on a different jaxlib — not fatal
        log.warning("could not configure CPU collectives: %s", e)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Idempotent `jax.distributed.initialize` with SLURM fallback.

    SLURM env contract (successor of the reference's TF_NUM_PS/TF_NUM_WORKERS
    env contract, reference scripts/run_dist_tf_daint.sh:4-27):
      SLURM_NTASKS → num_processes, SLURM_PROCID → process_id,
      SLURM_STEP_NODELIST first node:8476 → coordinator.
    """
    _enable_cpu_collectives()
    if coordinator_address is None and "SLURM_NTASKS" in os.environ and \
            int(os.environ["SLURM_NTASKS"]) > 1:
        num_processes = int(os.environ["SLURM_NTASKS"])
        process_id = int(os.environ["SLURM_PROCID"])
        nodelist = os.environ.get("SLURM_STEP_NODELIST",
                                  os.environ.get("SLURM_NODELIST", ""))
        first = _first_slurm_node(nodelist)
        coordinator_address = f"{first}:8476"
    from ..resilience.retry import retry_call

    def _preinitialized(e: BaseException) -> bool:
        # jax spells it "already initialized" in some paths and
        # "should only be called once" in State.initialize
        msg = str(e).lower()
        return "already" in msg or "only be called once" in msg

    def attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id)
        except Exception as e:
            # jax assigns its global client BEFORE connect(); without this
            # reset a retry would die on "should only be called once"
            # instead of re-attempting the connect (verified against
            # jax._src.distributed.State.initialize). NEVER shut down a
            # runtime that was initialized before our call, though — that
            # would tear down a live cluster connection
            if not _preinitialized(e):
                try:
                    jax.distributed.shutdown()
                except Exception:  # partially-initialized — best effort
                    pass
            raise

    try:
        # bounded retry: non-chief processes race the coordinator's bind at
        # job start, and transient DNS/connect failures are routine on big
        # clusters — the reference's grpc bootstrap just died there
        retry_call(
            attempt,
            retries=3, base_delay=1.0, max_delay=15.0,
            retry_on=(RuntimeError, ConnectionError, OSError),
            giveup=_preinitialized,
            description="jax.distributed.initialize")
        log.info("jax.distributed initialized: process %d/%d @ %s",
                 jax.process_index(), jax.process_count(), coordinator_address)
    except RuntimeError as e:  # already initialized before our call
        if not _preinitialized(e):
            raise
        log.info("jax.distributed already initialized")


def _first_slurm_node(nodelist: str) -> str:
    """Expand the first hostname from a SLURM nodelist like 'nid0[1234-1241]'.

    Minimal re-implementation of what the reference got from
    ``scontrol show hostnames`` (reference scripts/run_dist_tf_daint.sh:35).
    """
    if "[" not in nodelist:
        return nodelist.split(",")[0].strip()
    prefix, rest = nodelist.split("[", 1)
    spec = rest.split("]", 1)[0]
    first = spec.split(",")[0].split("-")[0]
    return f"{prefix}{first}"


def is_chief() -> bool:
    """Process 0 — successor of the reference's ``is_chief = task_index == 0``
    (reference resnet_cifar_main.py:323-335)."""
    return jax.process_index() == 0


# ---------------------------------------------------------------------------
# Elastic mesh generations (resilience/elastic.py; docs/resilience.md).
# A mesh GENERATION is one (membership, coordinator) epoch of the job.
# Every generation gets its own coordinator endpoint — the old service may
# linger half-dead on the chief (its shutdown blocks on the lost peer and
# is abandoned, below), so generation g must bind somewhere fresh.
# ---------------------------------------------------------------------------

def elastic_coordinator(base_address: str, generation: int,
                        port_stride: int = 7) -> str:
    """The epoch-suffixed coordinator contract: generation ``g`` lives at
    the base coordinator's host, port ``base + g * port_stride``.
    Deterministic from (base, g) alone so survivors and rejoining peers
    derive the SAME endpoint from the shared generation record without
    any further coordination. The chief (worker 0) hosts every
    generation's coordinator — a reshard that loses worker 0 is
    infeasible and falls back to exit 75."""
    host, _, port = base_address.rpartition(":")
    if not host:
        raise ValueError(
            f"coordinator_address {base_address!r} has no host:port — "
            "elastic generations need an explicit base endpoint")
    return f"{host}:{int(port) + generation * port_stride}"


def teardown_for_reshard(timeout_secs: float = 5.0) -> None:
    """Tear down a distributed runtime whose peers may be DEAD so this
    process can re-``initialize`` over the survivors.

    ``jax.distributed.shutdown`` is a barrier — against a dead peer the
    client's shutdown blocks forever, so it runs in an abandoned daemon
    thread (it touches only the local client/service references, never
    jax's global state, so giving up on it is safe). The main thread then
    resets ``jax._src.distributed.global_state`` by hand and drops every
    backend + compilation cache: all live ``jax.Array``s and jitted
    callables die with the old backend, which is why the elastic runtime
    rebuilds the Trainer and restores from the last committed checkpoint
    after calling this (verified against jax 0.4.37's State fields)."""
    from jax._src import distributed as _dist
    state = _dist.global_state
    client, service = state.client, state.service

    def _shutdown():
        for leg in (client, service):
            if leg is None:
                continue
            try:
                leg.shutdown()
            except Exception as e:  # dead-peer barrier errors — expected
                log.info("distributed teardown leg: %s: %s",
                         type(e).__name__, e)

    t = threading.Thread(target=_shutdown, daemon=True,
                         name="drt-dist-teardown")
    t.start()
    t.join(timeout=timeout_secs)
    if t.is_alive():
        log.warning("distributed shutdown still blocked on dead peers "
                    "after %.1fs — abandoning it (daemon thread)",
                    timeout_secs)
    state.client = None
    state.service = None
    state.coordinator_address = None
    state.process_id = 0
    state.num_processes = 1
    state.preemption_sync_manager = None
    import jax.extend.backend
    jax.extend.backend.clear_backends()
    jax.clear_caches()


def reinitialize(coordinator_address: str, num_processes: int,
                 process_id: int) -> None:
    """Re-enter the distributed runtime for a new mesh generation after
    ``teardown_for_reshard`` — the plain ``initialize`` ladder (same
    bounded retry; survivors race the chief's fresh bind exactly like a
    job start). Also the REJOINER's first init: a rejoiner has touched
    the local backend before this (device-count probes while waiting in
    the barrier), and ``jax.distributed.initialize`` refuses to run with
    live backends — drop them first (idempotent after a teardown)."""
    import jax.extend.backend
    jax.extend.backend.clear_backends()
    jax.clear_caches()
    initialize(coordinator_address=coordinator_address,
               num_processes=num_processes, process_id=process_id)
