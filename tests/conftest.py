"""Test harness: force an 8-device fake CPU mesh.

This is the successor of the reference's only integration test — the local
1ps+2wk CPU smoke cluster (reference scripts/submit_mac_dist.sh:9-39,
run_dist_tf_local.sh:14-22) — done the JAX way: 8 virtual host devices via
``xla_force_host_platform_device_count`` so every sharding/collective path
runs without TPU hardware (SURVEY.md §4 implication).

NOTE: this environment's sitecustomize registers an 'axon' TPU backend and
forces ``jax_platforms=axon,cpu`` via jax.config (which overrides the
JAX_PLATFORMS env var), so we must flip it back through jax.config, before
any backend is initialized.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (  # noqa: E402
    apply_virtual_cpu)

apply_virtual_cpu(8)  # XLA_FLAGS device count + jax.config platform flip

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8 and devs[0].platform == "cpu"
    return devs


@pytest.fixture(scope="session")
def mesh8(devices):
    """Pure data-parallel 8-device mesh (the reference's topology)."""
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    return create_mesh(MeshConfig(data=8))


@pytest.fixture(scope="session")
def mesh_dp_fsdp(devices):
    from distributed_resnet_tensorflow_tpu.parallel import create_mesh
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    return create_mesh(MeshConfig(data=4, fsdp=2))


@pytest.fixture()
def rng():
    return np.random.RandomState(0)
