#!/bin/bash
# SLURM submit shim — successor of the reference's per-(machine x dataset x
# backend) submit scripts (reference scripts/submit_cifar_daint_dist.sh etc.,
# SURVEY.md §2.19). One script: preset + overrides come from the command line.
#
#   sbatch -N <nodes> scripts/submit_tpu_slurm.sh <preset> [--set k=v ...]
#
# Every task runs the same SPMD program; parallel/distributed.py derives
# (coordinator, num_processes, process_id) from SLURM_* env vars — the ~200
# lines of host-list bash from the reference launcher are gone.
#SBATCH --job-name=drt-tpu
#SBATCH --ntasks-per-node=1
#SBATCH --time=12:00:00

set -euo pipefail

PRESET="${1:-cifar10_resnet50}"
shift || true

LOG_ROOT="${LOG_ROOT:-logs/${SLURM_JOB_NAME:-drt}-${SLURM_JOB_ID:-local}}"
mkdir -p "$LOG_ROOT"

# reference parity: optional checkpoint wipe via FRESH=1
# (reference submit_cifar_daint_dist.sh:67-73)
if [[ "${FRESH:-0}" == "1" ]]; then
  rm -rf "$LOG_ROOT/ckpt"
fi

srun --no-kill python -m distributed_resnet_tensorflow_tpu.main \
  --preset "$PRESET" \
  --set "log_root=$LOG_ROOT" \
  "$@"
