"""Tests for ops/ — GroupedBatchNorm semantics (cross-replica vs the
reference's per-replica BN, SURVEY.md §7 'hard parts')."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_resnet_tensorflow_tpu.ops import GroupedBatchNorm


def _apply(model, x, train=True):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if train:
        y, mut = model.apply(variables, x, train=True, mutable=["batch_stats"])
        return y, variables, mut["batch_stats"]
    return model.apply(variables, x, train=False), variables, None


def test_global_bn_normalizes():
    x = jnp.asarray(np.random.RandomState(0).randn(16, 4, 4, 8) * 3 + 5,
                    jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32, groups=1)
    y, _, _ = _apply(model, x)
    assert np.allclose(np.asarray(y).mean((0, 1, 2)), 0, atol=1e-4)
    assert np.allclose(np.asarray(y).std((0, 1, 2)), 1, atol=1e-2)


def test_grouped_bn_equals_per_shard_bn():
    """groups=G must reproduce running BN independently on each shard —
    the reference's per-replica semantics (reference README.md:38,54)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 4, 4, 3).astype(np.float32))
    grouped = GroupedBatchNorm(dtype=jnp.float32, groups=2)
    y, _, _ = _apply(grouped, x)

    single = GroupedBatchNorm(dtype=jnp.float32, groups=1)
    y0, _, _ = _apply(single, x[:4])
    y1, _, _ = _apply(single, x[4:])
    np.testing.assert_allclose(np.asarray(y),
                               np.concatenate([np.asarray(y0), np.asarray(y1)]),
                               rtol=1e-5, atol=1e-5)


def test_grouped_bn_running_stats_are_global():
    """Running stats must aggregate over ALL groups (law of total variance)
    so the evaluator sees one consistent moment set."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(32, 2, 2, 4).astype(np.float32) * 2 + 1)
    g = GroupedBatchNorm(dtype=jnp.float32, groups=4, momentum=0.0)
    _, _, stats = _apply(g, x)
    want_mean = np.asarray(x).mean((0, 1, 2))
    want_var = np.asarray(x).var((0, 1, 2))
    np.testing.assert_allclose(np.asarray(stats["mean"]), want_mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]), want_var, atol=1e-4)


def test_eval_uses_running_stats():
    x = jnp.ones((4, 2, 2, 3), jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    y = model.apply(variables, x, train=False)
    # fresh stats: mean 0 var 1 → y ≈ x
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-3)


def test_indivisible_groups_raise():
    import pytest
    x = jnp.ones((6, 2, 2, 3), jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32, groups=4)
    with pytest.raises(ValueError):
        model.init(jax.random.PRNGKey(0), x, train=True)


def test_mesh_axis_zero_collapses():
    """MeshConfig axis 0 == collapsed (docstring contract)."""
    from distributed_resnet_tensorflow_tpu.parallel import resolve_axis_sizes
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    sizes = resolve_axis_sizes(MeshConfig(data=-1, tensor=0), 8)
    assert sizes == (1, 8, 1, 1, 1, 1)
