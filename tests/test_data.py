"""Input pipeline tests — CIFAR binary parsing (both label layouts),
augmentation, standardization (covers reference cifar_input.py + the tf.data
paths, SURVEY.md §2.4-2.5, including the cifar100 fix)."""
import os

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.data import (
    augment_train, cifar_iterator, load_cifar, standardize,
    synthetic_iterator, learnable_synthetic_iterator)
from distributed_resnet_tensorflow_tpu.data.cifar import IMAGE_SIZE


def _write_fake_cifar10(tmp_path, n_per_file=20):
    rng = np.random.RandomState(0)
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        recs = np.zeros((n_per_file, 1 + 3072), np.uint8)
        recs[:, 0] = rng.randint(0, 10, n_per_file)
        recs[:, 1:] = rng.randint(0, 256, (n_per_file, 3072))
        recs.tofile(os.path.join(tmp_path, name))
    return str(tmp_path)


def _write_fake_cifar100(tmp_path, n=40):
    rng = np.random.RandomState(1)
    for name in ("train.bin", "test.bin"):
        recs = np.zeros((n, 2 + 3072), np.uint8)
        recs[:, 0] = rng.randint(0, 20, n)    # coarse
        recs[:, 1] = rng.randint(0, 100, n)   # fine
        recs[:, 2:] = rng.randint(0, 256, (n, 3072))
        recs.tofile(os.path.join(tmp_path, name))
    return str(tmp_path)


def test_load_cifar10(tmp_path):
    d = _write_fake_cifar10(tmp_path)
    images, labels = load_cifar("cifar10", d, "train")
    assert images.shape == (100, 32, 32, 3) and images.dtype == np.uint8
    assert labels.shape == (100,) and labels.max() < 10
    ev_images, ev_labels = load_cifar("cifar10", d, "eval")
    assert ev_images.shape == (20, 32, 32, 3)


def test_load_cifar100_uses_fine_label(tmp_path):
    """The reference's tf.data path one-hotted cifar100 to 10 classes
    (reference resnet_cifar_main.py:171 — a documented bug, SURVEY.md §2);
    here the fine label (byte 1) must be parsed (reference
    cifar_input.py:40-43 semantics)."""
    d = _write_fake_cifar100(tmp_path)
    images, labels = load_cifar("cifar100", d, "train")
    assert images.shape == (40, 32, 32, 3)
    assert labels.max() >= 20  # fine labels span 0..99, coarse only 0..19


def test_cifar_chw_to_nhwc_transpose(tmp_path):
    """Record layout is [label][R-plane][G-plane][B-plane]; pixel (0,0) R/G/B
    must land at images[0,0,0,:]."""
    rec = np.zeros((1, 1 + 3072), np.uint8)
    rec[0, 0] = 3
    rec[0, 1] = 11           # R(0,0)
    rec[0, 1 + 1024] = 22    # G(0,0)
    rec[0, 1 + 2048] = 33    # B(0,0)
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)]:
        rec.tofile(os.path.join(tmp_path, name))
    images, labels = load_cifar("cifar10", str(tmp_path), "train")
    assert labels[0] == 3
    assert list(images[0, 0, 0]) == [11, 22, 33]


def test_standardize_properties():
    rng = np.random.RandomState(0)
    x = rng.randint(0, 256, (4, 32, 32, 3)).astype(np.uint8)
    out = standardize(x)
    assert out.dtype == np.float32
    assert np.allclose(out.mean(axis=(1, 2, 3)), 0, atol=1e-4)
    assert np.allclose(out.std(axis=(1, 2, 3)), 1, atol=1e-2)
    # constant image: adjusted std kicks in, no NaN
    const = np.full((1, 32, 32, 3), 128, np.uint8)
    assert np.isfinite(standardize(const)).all()


def test_augment_shapes_and_flip(rng):
    x = rng.randint(0, 256, (16, 32, 32, 3)).astype(np.uint8)
    out = augment_train(x, rng)
    assert out.shape == (16, 32, 32, 3)
    # with pad 2 and random crop, output pixels come from the source image
    assert out.dtype == x.dtype


def test_cifar_iterator_and_sharding(tmp_path):
    d = _write_fake_cifar10(tmp_path)
    it0 = cifar_iterator("cifar10", d, 8, "train", seed=0,
                         shard_index=0, num_shards=2, prefetch=0)
    b = next(it0)
    assert b["images"].shape == (8, 32, 32, 3)
    assert b["images"].dtype == np.float32
    assert b["labels"].dtype == np.int32
    # eval iterator is deterministic order, no augmentation
    ev = cifar_iterator("cifar10", d, 10, "eval", prefetch=0)
    b1, b2 = next(ev), next(ev)
    assert b1["images"].shape == (10, 32, 32, 3)
    assert not np.array_equal(b1["labels"], b2["labels"]) or True


def test_synthetic_iterators():
    it = synthetic_iterator(4, 32, 10)
    b = next(it)
    assert b["images"].shape == (4, 32, 32, 3)
    li = learnable_synthetic_iterator(6, 8, 4)
    b = next(li)
    assert b["images"].shape == (6, 8, 8, 3)
    assert b["labels"].max() < 4


def test_eval_partial_batch_masked(tmp_path):
    """Final partial eval batch is padded + masked, not dropped (improvement
    over the reference evaluator, which ran a fixed 50x100 batches)."""
    d = _write_fake_cifar10(tmp_path)  # 20 eval images
    ev = cifar_iterator("cifar10", d, 16, "eval", prefetch=0)
    b1 = next(ev)
    assert "mask" not in b1
    b2 = next(ev)  # 4 real + 12 pad
    assert b2["images"].shape == (16, 32, 32, 3)
    assert b2["mask"].sum() == 4
    assert b2["mask"][:4].all() and not b2["mask"][4:].any()


def test_prefetch_propagates_errors():
    from distributed_resnet_tensorflow_tpu.data.cifar import _threaded_prefetch

    def bad_gen():
        yield {"x": 1}
        raise RuntimeError("boom")

    it = _threaded_prefetch(bad_gen(), 2)
    next(it)
    import pytest
    with pytest.raises(RuntimeError):
        next(it)


def test_device_prefetch():
    from distributed_resnet_tensorflow_tpu.data.device_prefetch import (
        device_prefetch)
    puts = []

    def put(x):
        puts.append(x)
        return x * 10

    out = list(device_prefetch(iter([1, 2, 3, 4]), put, depth=2))
    assert out == [10, 20, 30, 40]
    # transfers dispatched (on the staging thread) in order
    assert puts == [1, 2, 3, 4]

    # shorter than depth
    assert list(device_prefetch(iter([5]), put, depth=3)) == [50]
    # empty
    assert list(device_prefetch(iter([]), put, depth=2)) == []


def test_device_prefetch_slow_put_does_not_block_consumer():
    """The tentpole overlap contract: staging runs on a DEDICATED transfer
    thread, so a put() stuck on batch N must not block the consumer from
    draining already-staged batches."""
    import threading
    from distributed_resnet_tensorflow_tpu.data.device_prefetch import (
        device_prefetch)
    gate = threading.Event()

    def put(x):
        if x >= 3:
            # batch 3's transfer hangs until the test releases it
            assert gate.wait(10)
        return x * 10

    it = device_prefetch(iter([1, 2, 3, 4]), put, depth=2)
    got = []
    t = threading.Thread(target=lambda: got.extend([next(it), next(it)]))
    t.start()
    # batches 1 and 2 must arrive while put(3) is blocked on the gate
    t.join(timeout=5)
    assert not t.is_alive() and got == [10, 20]
    gate.set()
    assert list(it) == [30, 40]


def test_device_prefetch_close_during_inflight_staging_joins_workers():
    """close() while a put() is mid-flight must stop and join the staging
    thread (and any upstream source thread) without leaking."""
    import threading
    import time as _time
    from distributed_resnet_tensorflow_tpu.data.device_prefetch import (
        device_prefetch, threaded_iterator)

    def src():
        i = 0
        while True:
            yield i
            i += 1

    def slow_put(x):
        _time.sleep(0.05)
        return x

    existing = set(threading.enumerate())
    it = device_prefetch(
        threaded_iterator(src(), depth=2, name="drt-test-src"),
        slow_put, depth=2)
    assert next(it) == 0
    it.close()
    deadline = _time.time() + 5
    while _time.time() < deadline:
        leaked = [t for t in threading.enumerate() if t not in existing
                  and ("drt-device-stage" in t.name
                       or "drt-test-src" in t.name) and t.is_alive()]
        if not leaked:
            break
        _time.sleep(0.05)
    assert not leaked, leaked


def test_threaded_stacker_logs_dropped_tail(caplog):
    """A trailing partial group of < k batches cannot be fused-dispatched
    and is dropped — but never silently (no-silent-caps rule)."""
    import logging
    from distributed_resnet_tensorflow_tpu.data.device_prefetch import (
        threaded_stacker)
    batches = [{"x": np.full((2,), i)} for i in range(7)]
    with caplog.at_level(
            logging.WARNING,
            logger="distributed_resnet_tensorflow_tpu.data.device_prefetch"):
        out = list(threaded_stacker(iter(batches), 3, depth=2))
    assert len(out) == 2  # 2 full groups; the 1-batch tail is dropped
    assert any("dropping 1 trailing batch" in r.message
               for r in caplog.records)
    # exact multiple: no warning
    caplog.clear()
    with caplog.at_level(
            logging.WARNING,
            logger="distributed_resnet_tensorflow_tpu.data.device_prefetch"):
        out = list(threaded_stacker(iter(batches[:6]), 3, depth=2))
    assert len(out) == 2
    assert not any("trailing batch" in r.message for r in caplog.records)
