"""Measured MoE step time on real TPU — dense MLP vs Switch top-1 vs top-2.

Single chip (expert weights resident, no expert axis to shard over), ViT
encoder at a fixed token budget; reports ms/step of the full train step so
the one-hot dispatch/combine cost (O(N·E·C) einsums riding the MXU) is a
measured number, not a guess. Writes docs/moe_r3.json.

    python tools/bench_moe.py
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def build(num_experts: int, top_k: int, bs=32, image=64, patch=4,
          dispatch="auto"):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 16
    cfg.model.vit_dim = 256
    cfg.model.vit_depth = 6
    cfg.model.vit_heads = 4
    cfg.model.vit_num_experts = num_experts
    cfg.model.vit_moe_top_k = top_k
    cfg.model.vit_moe_dispatch = dispatch
    cfg.data.image_size = image
    cfg.model.vit_patch_size = patch
    cfg.train.batch_size = bs
    k = 8
    cfg.train.steps_per_loop = k
    cfg.mesh.data = len(jax.devices())
    tr = Trainer(cfg)
    tr.init_state()
    fn = tr.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, image, image, 3).astype(np.float32),
        "labels": rng.randint(0, 16, (k, bs)).astype(np.int32),
    }, tr.mesh)
    return tr, fn, batch, k


def ms_per_step(tr, fn, batch, k, loops=5, reps=3):
    state = tr.state
    for _ in range(2):
        state, _ = fn(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, _ = fn(state, batch)
        jax.block_until_ready(state.params)
        best = min(best, (time.perf_counter() - t0) / (loops * k))
    return best * 1e3


def main():
    out = {"device": jax.devices()[0].device_kind,
           "tokens_per_batch": 32 * (64 // 4) ** 2, "configs": {}}
    for name, (e, tk, disp) in (("dense_mlp", (0, 1, "auto")),
                                ("moe_e8_top1_einsum", (8, 1, "einsum")),
                                ("moe_e8_top1_gather", (8, 1, "gather")),
                                ("moe_e8_top2_gather", (8, 2, "gather"))):
        tr, fn, batch, k = build(e, tk, dispatch=disp)
        ms = ms_per_step(tr, fn, batch, k)
        out["configs"][name] = round(ms, 3)
        print(f"{name:>12}: {ms:7.2f} ms/step", flush=True)
    d = out["configs"]
    out["gather_vs_einsum"] = round(
        d["moe_e8_top1_einsum"] / d["moe_e8_top1_gather"], 2)
    out["moe_top1_vs_dense"] = round(
        d["moe_e8_top1_gather"] / d["dense_mlp"], 2)
    out_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "moe_r3.json")
    with open(out_path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
