"""NaN/Inf sentinel: rollback + LR back-off instead of a dead run.

The reference's only divergence story was a human noticing ``loss = nan`` in
the 20-step log while the cluster kept burning node-hours (SURVEY.md §4.4).
round-0 added detection (train/hooks.py NanGuardHook raises); this module
adds RECOVERY: when the guard trips, roll the TrainState back to the last
good committed checkpoint, re-seed the data stream (so the exact batch
sequence that blew up is not replayed), shrink the LR schedule by a
configurable back-off factor, and keep training — giving up loudly after
``max_strikes`` rollbacks so a genuinely broken run still fails.

Large-batch recipes hit transient loss spikes / non-finite steps routinely
(LARS at bs=32k, arXiv:1811.05233 §4 discusses exactly this class of
instability); a bounded automatic retry converts "page the operator" into a
log line.
"""
from __future__ import annotations

import logging
from typing import Callable, Iterator, Optional, Tuple

from ..telemetry.tracer import span
from ..train.hooks import NanGuardHook

log = logging.getLogger(__name__)


class TooManyNanRetries(RuntimeError):
    """The run kept producing non-finite loss after every allowed rollback."""


def train_with_nan_recovery(
        trainer, manager,
        iter_factory: Callable[[int], Iterator],
        num_steps: Optional[int],
        hooks: Tuple = (),
        start_step: int = 0,
        *,
        max_strikes: int = 3,
        lr_backoff: float = 0.5,
        stop_fn: Optional[Callable[[], bool]] = None):
    """``trainer.train`` wrapped in the rollback-retry policy.

    ``iter_factory(attempt)`` builds the training stream; attempt 0 is the
    original run, attempt N>0 follows the N-th rollback and must re-seed /
    re-offset the stream. The guard raises out of ``trainer.train`` (hooks
    run at step boundaries); recovery restores the newest checkpoint that
    verifies (checkpoint/manager.py fallback order), multiplies the LR
    schedule by ``lr_backoff**strikes``, and resumes from the restored step
    — or from a fresh init at step 0 when nothing was ever committed.

    NOTE the window: a checkpoint saved between the non-finite step and the
    guard's next check would itself be poisoned, so keep the guard cadence
    (resilience.nan_check_every_steps) at or below the save cadence.
    """
    strikes = 0
    data_iter = iter_factory(0)
    step = start_step
    while True:
        try:
            return trainer.train(data_iter, num_steps=num_steps,
                                 hooks=hooks, start_step=step,
                                 stop_fn=stop_fn)
        except NanGuardHook.NanLossError as e:
            strikes += 1
            if strikes > max_strikes:
                raise TooManyNanRetries(
                    f"non-finite loss persisted through {max_strikes} "
                    f"rollback(s) with LR backed off to "
                    f"{lr_backoff ** max_strikes:g}x — giving up: {e}"
                ) from e
            # goodput: rollback-recovery wall is "restart", not compute
            # (telemetry/goodput.py)
            with span("restore", category="restart"):
                trainer.state, restored = manager.restore(trainer.state)
                if restored is None:
                    # nothing ever committed: restart from a fresh init
                    trainer.init_state()
                    step = 0
                else:
                    step = int(trainer.state.step)
            # rewind every hook's cadence to the restored step: a guard
            # whose _last still points at the trip step would be blind for
            # the whole replayed span — long enough for a cadence save to
            # commit NaN params with a valid manifest
            for h in hooks:
                rollback = getattr(h, "rollback_to", None)
                if rollback is not None:
                    rollback(step)
            scale = lr_backoff ** strikes
            trainer.scale_lr(scale)
            data_iter = iter_factory(strikes)
            log.warning(
                "NaN sentinel strike %d/%d: %s — rolled back to step %d, "
                "LR scaled to %gx, data stream re-seeded (attempt %d)",
                strikes, max_strikes, e, step, scale, strikes)
