from .synthetic import synthetic_iterator, learnable_synthetic_iterator  # noqa: F401
from .cifar import cifar_iterator, load_cifar, standardize, augment_train  # noqa: F401
from .device_dataset import (  # noqa: F401
    device_dataset_enabled, epoch_index_iterator)


def device_augment_enabled(cfg, mode: str = "train") -> bool:
    """Single source of truth for who augments/standardizes — the iterator
    (yields raw uint8) and the Trainer (applies ops/augment in the jitted
    step) MUST agree, so both call this.

    cifar*: the device does crop/flip/standardize (ops/augment.py).
    imagenet: the device does the VGG standardize only (the geometric ops
    are host-side, tied to per-image source sizes); the iterator then ships
    uint8 crops — 4× smaller transfers, no host float pass. Round 4: the
    imagenet EVAL path gets the same treatment (the standardize is
    deterministic, so the only question is where the float pass runs;
    make_eval_step applies it on device) — cifar eval stays host-side
    (its standardize is per-image moments, fused into the host parse)."""
    if cfg.data.dataset not in ("cifar10", "cifar100", "imagenet"):
        return False
    if mode != "train" and cfg.data.dataset != "imagenet":
        return False
    setting = cfg.data.device_augment
    if setting == "on":
        return True
    if setting == "off":
        return False
    if setting != "auto":
        raise ValueError(f"unknown device_augment setting {setting!r}")
    import jax
    return jax.default_backend() == "tpu"


def create_input_iterator(cfg, mode: str = "train", shard_index: int = 0,
                          num_shards: int = 1, batch_size=None,
                          deterministic: bool = False):
    """Input factory — the one definition replacing the 4 near-identical
    ``input_fn`` copies in the reference mains (SURVEY.md §1 note).

    ``deterministic``: required when several processes feed the SAME
    replicated batch slice (non-batch mesh axis over processes) — the
    imagenet pipeline's parallel decode is otherwise completion-ordered
    (see imagenet_iterator). The synthetic and cifar paths are
    deterministic by construction (seeded single-generator streams)."""
    d = cfg.data
    bs = batch_size or (cfg.train.batch_size if mode == "train"
                        else d.eval_batch_size)
    if d.dataset == "synthetic":
        return synthetic_iterator(bs, d.image_size, cfg.model.num_classes,
                                  seed=cfg.train.seed)
    if d.dataset in ("cifar10", "cifar100"):
        return cifar_iterator(d.dataset, d.data_dir, bs, mode,
                              seed=cfg.train.seed, shard_index=shard_index,
                              num_shards=num_shards,
                              prefetch=d.prefetch_batches,
                              use_native=d.use_native_loader,
                              device_augment=device_augment_enabled(cfg, mode))
    if d.dataset == "imagenet":
        from .imagenet import imagenet_iterator
        return imagenet_iterator(d.data_dir, bs, mode, image_size=d.image_size,
                                 seed=cfg.train.seed, shard_index=shard_index,
                                 num_shards=num_shards,
                                 num_decode_threads=d.num_parallel_calls,
                                 prefetch_batches=d.prefetch_batches,
                                 use_native=d.use_native_loader,
                                 device_standardize=device_augment_enabled(
                                     cfg, mode),
                                 decode_processes=d.decode_processes,
                                 deterministic=deterministic,
                                 max_corrupt_records=d.max_corrupt_records,
                                 verify_crc=d.verify_crc)
    raise ValueError(f"unknown dataset {d.dataset!r}")
