"""Shardcheck: static elaboration + project-invariant linting.

The most expensive bug class on a shared cluster is the one that turns a
20-minute queue wait into a step-1 crash: a ``PartitionSpec`` that does
not match the mesh, a ``--set`` knob that silently does not exist, or a
cross-thread dispatch that deadlocks a collective. This package catches
all three in seconds, on a laptop, with zero data and zero compute:

  * ``elaborate``   — virtual-device mesh + ``jax.eval_shape`` over the
                      real train/eval steps and the restore contract for
                      every preset × mesh layout (docs/static_analysis.md);
  * ``lint``        — AST rules for the invariants this codebase learned
                      the hard way (one rule per module under ``rules/``);
  * ``dispatch_sanitizer`` — opt-in runtime guard for the one-thread
                      multi-device dispatch constraint
                      (docs/input_pipeline.md threading model).

Surfaced as ``python -m distributed_resnet_tensorflow_tpu.main check``
and the pre-submit gate ``scripts/analysis_gate.sh``.
"""
from .report import Finding, format_findings  # noqa: F401
