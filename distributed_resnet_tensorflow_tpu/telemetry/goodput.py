"""Goodput accounting: classify train-loop wall time by where it went.

The break-down that dominates at scale (arXiv:1711.00705): a cluster's
billed wall-clock splits into useful compute vs input-wait vs checkpoint
stalls vs eval rounds vs restart overhead — and the reference could not
measure ANY of it (stdout logs + TensorBoard scalars only, SURVEY.md
§2.15). Here every second of the train loop lands in exactly one bucket:

  * ``input_wait``  — loop blocked on the next device batch
    (``span("input.wait")`` in train/loop.py),
  * ``checkpoint``  — loop blocked in save()/wait_until_finished
    (checkpoint/manager.py),
  * ``eval``        — in-loop evaluation rounds (Trainer.evaluate),
  * ``restart``     — NaN-rollback restores (resilience/sentinel.py),
  * ``stall``       — watchdog-attributed dead time (hang verdicts,
    resilience/watchdog.py),
  * ``reshard``     — elastic mesh-generation transitions: barrier +
    teardown + re-init + restore + rebuild (resilience/elastic.py),
  * ``compute``     — everything else: the remainder of the wall interval.
    Remainder-as-compute is the honest choice under async dispatch — the
    loop thread does not block per step, so its non-waiting wall time IS
    the window in which the device pipeline runs.

Categorized spans (telemetry/tracer.py) feed ``GoodputMeter.add``; the
chief's ``GoodputHook`` (train/hooks.py) emits one registered
``{"event": "goodput"}`` metrics row per summary cadence with per-category
seconds and percentages (summing to ~100% of the interval's wall by
construction). ``bench.py``'s goodput row and ``main.py monitor`` consume
the same numbers — ROADMAP open items 2 (input gap) and 5 (zero-stall
persistence) are measured against exactly these buckets.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

#: the classification buckets, in display order. "compute" is always the
#: interval remainder; the others are measured from categorized spans.
CATEGORIES = ("compute", "input_wait", "checkpoint", "eval", "stall",
              "restart", "reshard")

#: the buckets spans may charge (everything but the remainder)
MEASURED_CATEGORIES = CATEGORIES[1:]


class GoodputMeter:
    """Thread-safe cumulative seconds per category + interval summaries.

    ``add`` is the span-exit hot path (one lock + one float add);
    ``interval()`` differences the cumulative totals against the previous
    call and classifies the wall time in between; ``rebase()`` restarts
    the window without emitting (call at train-segment start so compile /
    restore time before step 1 is not billed as compute).
    """

    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {c: 0.0 for c in
                                          MEASURED_CATEGORIES}
        self._mark_t: Optional[float] = None
        self._mark_totals: Dict[str, float] = dict(self._totals)

    def add(self, category: str, seconds: float) -> None:
        with self._lock:
            # unknown categories accumulate too (forward compatibility);
            # interval() only reports the registered set
            self._totals[category] = \
                self._totals.get(category, 0.0) + seconds

    def snapshot(self) -> Dict[str, float]:
        """Cumulative measured seconds per category since process start."""
        with self._lock:
            return dict(self._totals)

    def rebase(self) -> None:
        """Restart the interval window at now."""
        with self._lock:
            self._mark_t = self._clock()
            self._mark_totals = dict(self._totals)

    def interval(self) -> Dict[str, object]:
        """Classify the wall time since the last interval()/rebase().

        Returns ``{"wall_secs", "seconds": {cat: s}, "pct": {cat: p}}``
        with ``compute`` = wall − Σ(measured), clamped at 0 (overlapping
        charges from a second thread can only shrink compute, never push
        the sum past 100%: percentages are normalized over max(wall, Σ)).
        The first call after construction measures from the first
        ``rebase()`` — without one it returns an empty interval."""
        now = self._clock()
        with self._lock:
            if self._mark_t is None:
                self._mark_t = now
                self._mark_totals = dict(self._totals)
                return {"wall_secs": 0.0,
                        "seconds": {c: 0.0 for c in CATEGORIES},
                        "pct": {c: 0.0 for c in CATEGORIES}}
            wall = max(0.0, now - self._mark_t)
            delta = {c: self._totals.get(c, 0.0)
                     - self._mark_totals.get(c, 0.0)
                     for c in MEASURED_CATEGORIES}
            self._mark_t = now
            self._mark_totals = dict(self._totals)
        measured = sum(delta.values())
        seconds = {"compute": max(0.0, wall - measured), **delta}
        denom = max(wall, measured, 1e-9)
        pct = {c: 100.0 * s / denom for c, s in seconds.items()}
        return {
            "wall_secs": round(wall, 4),
            "seconds": {c: round(seconds[c], 4) for c in CATEGORIES},
            "pct": {c: round(pct[c], 2) for c in CATEGORIES},
        }


#: the process-global meter categorized spans feed
goodput = GoodputMeter()
