"""ctypes bindings for the native C++ data loader (native/dataloader.cc).

The C++ tier replaces what the reference got from TensorFlow's native input
runtime (queue runners / tf.data C++, SURVEY.md L0-L1): CRC32C, CIFAR binary
parsing, and a multithreaded TFRecord prefetcher with a bounded ring buffer.

Auto-builds with ``make`` on first use if a toolchain is present; callers can
always fall back to the pure-python paths (data/cifar.py, data/tfrecord.py),
which are behavior-identical (tests assert this).
"""
from __future__ import annotations

import ctypes
import logging
import os
import subprocess
from typing import Iterator, List, Optional, Tuple

import numpy as np

log = logging.getLogger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "native")
_SO_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libdrtdata.so"))
_lib = None


class NativeUnavailable(RuntimeError):
    pass


def _build() -> bool:
    try:
        subprocess.run(["make", "-C", os.path.abspath(_NATIVE_DIR)],
                       check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO_PATH)
    except Exception as e:  # toolchain missing etc.
        log.info("native loader build failed: %s", e)
        return False


def _so_exports(symbol: bytes) -> bool:
    """Probe the on-disk .so for an exported symbol WITHOUT dlopen-ing it.

    Staleness must be decided before the first ``ctypes.CDLL``: glibc caches
    dlopen handles by device/inode and ``make`` relinks in place, so once the
    old mapping exists a rebuild+re-CDLL hands back the stale symbol table.

    Asks ``nm -D`` for the dynamic symbol table (exact-token match, so a
    string literal or archive-member occurrence of the name elsewhere in
    the file can't report a stale pre-JPEG build as fresh); falls back to
    a raw substring scan only when binutils is unavailable."""
    try:
        out = subprocess.run(["nm", "-D", "--defined-only", _SO_PATH],
                             capture_output=True, timeout=30)
        if out.returncode == 0 and out.stdout:
            return any(line.split()[-1] == symbol.decode()
                       for line in out.stdout.decode(errors="replace")
                       .splitlines() if line.split())
    except Exception:
        pass
    try:
        with open(_SO_PATH, "rb") as f:
            return symbol in f.read()
    except OSError:
        return False


def load_library(auto_build: bool = True) -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    stale = os.path.exists(_SO_PATH) and not _so_exports(b"drt_prefetch_stop")
    if not os.path.exists(_SO_PATH) or stale:
        if not (auto_build and _build()) and not os.path.exists(_SO_PATH):
            raise NativeUnavailable(
                f"{_SO_PATH} not built (run `make -C {_NATIVE_DIR}`)")
    try:
        lib = ctypes.CDLL(_SO_PATH)
    except OSError as e:
        # corrupt / wrong-arch / partially-written .so: the documented
        # contract is silent fallback to the python paths, so map the
        # loader error onto the exception callers already handle
        raise NativeUnavailable(f"{_SO_PATH} failed to load: {e}") from e
    if not hasattr(lib, "drt_prefetch_stop"):
        # stale build mapped and the rebuild failed (no toolchain, or
        # another component dlopened the old file first — glibc caches by
        # inode). The bindings below would AttributeError; surface the
        # canonical exception so callers fall back to the python paths.
        raise NativeUnavailable(
            f"{_SO_PATH} is a stale build missing drt_prefetch_stop and "
            f"could not be rebuilt (run `make -C {_NATIVE_DIR}`)")
    if not hasattr(lib, "drt_has_jpeg"):
        # pre-JPEG-tier build still mapped (rebuild failed, or another
        # component dlopened the stale file first) — the JPEG fast path is
        # unavailable for this process; core bindings below still work
        log.warning("libdrtdata.so predates the JPEG tier and cannot be "
                    "reloaded in-process; JPEG decode falls back to python")
    lib.drt_crc32c.restype = ctypes.c_uint32
    lib.drt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.drt_masked_crc32c.restype = ctypes.c_uint32
    lib.drt_masked_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.drt_cifar_load.restype = ctypes.c_int64
    lib.drt_cifar_load.argtypes = [
        ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int64]
    lib.drt_prefetch_create.restype = ctypes.c_void_p
    lib.drt_prefetch_create.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.c_int32, ctypes.c_int32,
        ctypes.c_int32, ctypes.c_int32]
    lib.drt_prefetch_next.restype = ctypes.c_int64
    lib.drt_prefetch_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64)]
    lib.drt_prefetch_crc_errors.restype = ctypes.c_int64
    lib.drt_prefetch_crc_errors.argtypes = [ctypes.c_void_p]
    lib.drt_prefetch_truncated.restype = ctypes.c_int64
    lib.drt_prefetch_truncated.argtypes = [ctypes.c_void_p]
    lib.drt_prefetch_stop.restype = None
    lib.drt_prefetch_stop.argtypes = [ctypes.c_void_p]
    lib.drt_prefetch_destroy.restype = None
    lib.drt_prefetch_destroy.argtypes = [ctypes.c_void_p]
    if hasattr(lib, "drt_has_jpeg"):
        lib.drt_has_jpeg.restype = ctypes.c_int
        lib.drt_has_jpeg.argtypes = []
    if hasattr(lib, "drt_has_jpeg") and lib.drt_has_jpeg():
        lib.drt_decode_resize_crop.restype = ctypes.c_int
        lib.drt_decode_resize_crop.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8)]
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        load_library()
        return True
    except NativeUnavailable:
        return False


def crc32c(data: bytes) -> int:
    return load_library().drt_crc32c(data, len(data))


def masked_crc32c(data: bytes) -> int:
    return load_library().drt_masked_crc32c(data, len(data))


def load_cifar_native(path: str, label_bytes: int, label_offset: int,
                      max_records: int = 0
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """CIFAR binary file → (HWC uint8 images, int32 labels), parsed in C++.

    ``max_records`` 0 (default) sizes the buffers from the file itself, so
    files larger than the standard 60k-record datasets load in full —
    identical output to the python parser, which has no cap."""
    lib = load_library()
    if max_records <= 0:
        record_len = label_bytes + 32 * 32 * 3
        max_records = max(1, os.path.getsize(path) // record_len)
    images = np.empty((max_records, 32, 32, 3), np.uint8)
    labels = np.empty((max_records,), np.int32)
    n = lib.drt_cifar_load(
        path.encode(), label_bytes, label_offset,
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        max_records)
    if n < 0:
        raise FileNotFoundError(path)
    return images[:n].copy(), labels[:n].copy()


class NativePrefetcher:
    """Iterate raw TFRecord payloads produced by C++ reader threads.

    Thread contract: one consumer thread iterates; ``close()`` may run
    from another thread (teardown, __del__). Protocol: close() nulls the
    handle under ``_lock`` (no NEW C calls can start), calls
    ``drt_prefetch_stop`` (wakes a consumer BLOCKED inside
    ``drt_prefetch_next`` — the stop flag satisfies its wait predicate),
    waits for the in-flight counter to drain, and only then destroys —
    so the native object is never freed under a live call and close()
    never waits on data arrival. A damaged shard is LOUD: mid-record
    truncation raises IOError at end of stream (matching
    data/tfrecord.py), and skipped-CRC records warn."""

    def __init__(self, paths: List[str], num_threads: int = 4,
                 capacity: int = 512, verify_crc: bool = False):
        import threading
        self._lock = threading.Lock()  # first: __del__ may see a partial init
        self._inflight = 0
        self._handle = None
        self._final_crc_errors = 0
        self._final_truncated = 0
        self._lib = load_library()
        arr = (ctypes.c_char_p * len(paths))(*[p.encode() for p in paths])

        def create():
            handle = self._lib.drt_prefetch_create(
                arr, len(paths), num_threads, capacity, int(verify_crc))
            if not handle:
                raise NativeUnavailable("prefetcher creation failed")
            return handle

        # bounded retry (resilience/retry.py): creation opens every shard,
        # and a transient FS hiccup there shouldn't abort the whole run —
        # persistent failure still raises NativeUnavailable for the
        # documented python fallback
        from ..resilience.retry import retry_call
        self._handle = retry_call(
            create, retries=2, base_delay=0.1,
            retry_on=(NativeUnavailable,),
            description="native prefetcher open")
        self._buf = np.empty(1 << 20, np.uint8)  # 1 MB, grown on demand

    def __iter__(self) -> Iterator[bytes]:
        return self

    def __next__(self) -> bytes:
        while True:
            with self._lock:
                if self._handle is None:
                    raise StopIteration
                self._inflight += 1
                h = self._handle
            truncated = crc = 0
            try:
                needed = ctypes.c_int64(0)
                n = self._lib.drt_prefetch_next(
                    h,
                    self._buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    self._buf.size, ctypes.byref(needed))
                if n == 0:  # end of stream: read the error counters while
                    truncated = self._lib.drt_prefetch_truncated(h)
                    crc = self._lib.drt_prefetch_crc_errors(h)  # h is live
            finally:
                with self._lock:
                    self._inflight -= 1
            if n == 0:
                if crc:
                    log.warning("native prefetcher skipped %d record(s) "
                                "with bad CRC", crc)
                if truncated:
                    raise IOError(
                        f"truncated/corrupt TFRecord framing in {truncated} "
                        "file(s) — stream is incomplete (the python reader "
                        "raises the same way)")
                raise StopIteration
            if n == -1:
                self._buf = np.empty(int(needed.value) * 2, np.uint8)
                continue
            return bytes(self._buf[:n])

    @property
    def crc_errors(self) -> int:
        with self._lock:
            if self._handle is None:
                return self._final_crc_errors
            return self._lib.drt_prefetch_crc_errors(self._handle)

    @property
    def truncated(self) -> int:
        with self._lock:
            if self._handle is None:
                return self._final_truncated
            return self._lib.drt_prefetch_truncated(self._handle)

    def close(self, drain_timeout: float = 5.0) -> None:
        import time
        with self._lock:
            h, self._handle = self._handle, None
        if h is None:
            return
        # wake a consumer blocked inside drt_prefetch_next; it returns 0
        # and decrements _inflight (its properties reads use the local h,
        # still alive until destroy below)
        self._lib.drt_prefetch_stop(h)
        deadline = time.monotonic() + drain_timeout
        while True:
            with self._lock:
                if self._inflight == 0:
                    break
            if time.monotonic() >= deadline:
                # a missed wakeup in the native layer must not turn
                # teardown (incl. __del__ at interpreter exit) into an
                # infinite hang: leak the native object — destroying it
                # under a live drt_prefetch_next call would be a
                # use-after-free (ADVICE r5)
                with self._lock:
                    inflight = self._inflight
                log.warning(
                    "NativePrefetcher.close(): %d in-flight native call(s) "
                    "did not drain within %.1fs; leaking the native "
                    "prefetcher handle instead of risking a use-after-free",
                    inflight, drain_timeout)
                self._final_crc_errors = self._lib.drt_prefetch_crc_errors(h)
                self._final_truncated = self._lib.drt_prefetch_truncated(h)
                return
            time.sleep(0.001)
        self._final_crc_errors = self._lib.drt_prefetch_crc_errors(h)
        self._final_truncated = self._lib.drt_prefetch_truncated(h)
        self._lib.drt_prefetch_destroy(h)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def native_jpeg_available() -> bool:
    """True iff the .so was built against libjpeg (drt_has_jpeg)."""
    try:
        lib = load_library()
        return bool(getattr(lib, "drt_has_jpeg", lambda: 0)())
    except NativeUnavailable:
        return False


def decode_resize_crop_native(data: bytes, resize_side: int, top: int,
                              left: int, out_size: int, flip: bool
                              ) -> Optional[np.ndarray]:
    """Fused C++ ImageNet transform: DCT-scaled JPEG decode + bilinear
    sample of exactly the (out_size², 3) crop window at (top, left) of the
    conceptual resized image, flipped when asked. The ctypes call releases
    the GIL, so a Python thread pool around this decodes in true parallel.
    Returns None when the content needs the PIL fallback (non-JPEG, CMYK,
    corrupt) or the library lacks libjpeg."""
    try:
        lib = load_library()
    except NativeUnavailable:
        return None
    if not getattr(lib, "drt_has_jpeg", lambda: 0)():
        return None
    out = np.empty((out_size, out_size, 3), np.uint8)
    rc = lib.drt_decode_resize_crop(
        data, len(data), resize_side, top, left, out_size, int(flip),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out if rc == 0 else None
