"""Round-5 CIFAR flagship anatomy (VERDICT r4 weak #2 / next #5).

The CIFAR-10 ResNet-50 headline (the reference's flagship workload,
reference README.md:22-33) has run every round at ~0.17 MFU with no per-op
account of where the non-MXU time goes at 32² — this script gives it the
same treatment ImageNet got in rounds 3-4:

  * bs sweep 128/512/2048 (is the flagship recipe's gbs=128 dispatch- or
    compute-bound?),
  * k (steps_per_loop) sweep at bs=128 (dispatch amortization over the
    tunnel),
  * norm sweep (what share of the 32² step is BN stat work),
  * per-op xplane trace at bs=128 (category breakdown, MXU share).

Writes docs/perf_cifar_r5.json. Reuses bench.py's harness conventions
(same augment-in-step path as the headline row) and profile_trace.op_table.

    python tools/profile_cifar_r5.py [sweep] [kscan] [norm] [trace]
"""
from __future__ import annotations

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import jax  # noqa: E402
import numpy as np  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

OUT = os.path.join(REPO, "docs", "perf_cifar_r5.json")


def build_step(bs: int, k: int, norm: str = "batch"):
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("cifar10_resnet50")
    # same step as bench_cifar: dataset cifar10 → device-side augmentation
    # runs inside the jitted step (ops/augment.py)
    cfg.data.data_dir = "/tmp/drt_bench_cifar"
    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    cfg.model.norm = norm
    if os.environ.get("DRT_WIDTH"):
        # channel-width lever: same 32² topology, width× channels — the
        # MXU-lane-filling hypothesis test (16/32/64 channels use at most
        # half the 128-wide systolic array; width 10 → 160/320/640 fills it)
        cfg.model.resnet_size = 28
        cfg.model.width_multiplier = int(os.environ["DRT_WIDTH"])
    cfg.mesh.data = len(jax.devices())
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, 32, 32, 3).astype(np.float32),
        "labels": rng.randint(0, 10, (k, bs)).astype(np.int32),
    }, trainer.mesh)
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]},
                      trainer.mesh)
    return trainer, multi_fn, batch, one


def measure(bs: int, k: int = 20, loops: int = 10, reps: int = 5,
            norm: str = "batch"):
    from distributed_resnet_tensorflow_tpu.utils import profiling
    trainer, multi_fn, batch, one = build_step(bs, k, norm)
    state = trainer.state
    t_c = time.perf_counter()
    for _ in range(2):
        state, _ = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    compile_s = time.perf_counter() - t_c
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, _ = multi_fn(state, batch)
        jax.block_until_ready(state.params)
        best = min(best, time.perf_counter() - t0)
    sps = loops * k / best
    step_flops = profiling.flops_per_step(
        trainer.jitted_train_step(), state, one)
    mfu = profiling.mfu(sps, step_flops) if step_flops else None
    row = {"batch_size": bs, "k": k, "norm": norm,
           "steps_per_sec": round(sps, 2),
           "images_per_sec": round(sps * bs, 1),
           "ms_per_step": round(1000.0 / sps, 3),
           "mfu": round(mfu, 4) if mfu else None,
           "step_flops": step_flops,
           "compile_s": round(compile_s, 1)}
    print(json.dumps(row), flush=True)
    return row


def trace(bs: int, k: int, top: int = 20):
    from profile_trace import op_table
    logdir = f"/tmp/drt_cifar_trace_bs{bs}"
    trainer, multi_fn, batch, _one = build_step(bs, k)
    state = trainer.state
    for _ in range(2):
        state, _ = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    with jax.profiler.trace(logdir):
        for _ in range(2):
            state, _ = multi_fn(state, batch)
        jax.block_until_ready(state.params)
    fams, _insts = op_table(logdir, top)
    steps = 2 * k
    cats = {}
    for row in fams:
        cats[row["category"]] = cats.get(row["category"], 0.0) \
            + row["self_us"]
    total = sum(cats.values())
    return {
        "per_step_us_by_category": {
            c: round(us / steps, 1) for c, us in
            sorted(cats.items(), key=lambda kv: -kv[1])},
        "category_share": {
            c: round(us / total, 3) for c, us in
            sorted(cats.items(), key=lambda kv: -kv[1])},
        "top_op_families_per_step_us": [
            {"op": r["op"], "category": r["category"],
             "us": round(r["self_us"] / steps, 1), "n": r["n"] // steps}
            for r in fams[:top]],
    }


def main(argv):
    want = set(argv) or {"sweep", "kscan", "norm", "trace"}
    out = {}
    if os.path.exists(OUT):
        out = json.load(open(OUT))
    out["device"] = jax.devices()[0].device_kind
    if "sweep" in want:
        out["bs_sweep"] = [measure(bs) for bs in (128, 512, 2048)]
    if "kscan" in want:
        out["k_scan_bs128"] = [
            measure(128, k=k, loops=max(1, 200 // k)) for k in (1, 5, 20, 60)]
    if "norm" in want:
        out["norm_bs128"] = [measure(128, norm=n)
                             for n in ("frozen", "group")]
    if "trace" in want:
        out["trace_bs128_k20"] = trace(128, 20)
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", OUT)


if __name__ == "__main__":
    main(sys.argv[1:])
