"""Switch MoE tests (models/moe.py) — routing/capacity semantics, expert-axis
sharding equivalence, and the Trainer integration with the aux loss."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
from distributed_resnet_tensorflow_tpu.parallel import create_mesh
from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig, get_preset


def _mesh(**axes):
    return create_mesh(MeshConfig(**axes))


def test_single_expert_equals_plain_mlp():
    """E=1 with ample capacity routes every token to the one expert with
    gate 1.0 (softmax over one logit), so SwitchMlp == its MLP."""
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    moe = SwitchMlp(num_experts=1, capacity_factor=1.0, dtype=jnp.float32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    got = moe.apply(variables, x)

    p = variables["params"]
    import flax.linen as nn
    h = x @ p["w1"][0] + p["bias1"][0]
    want = nn.gelu(h) @ p["w2"][0] + p["bias2"][0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_capacity_drop_zeroes_overflow_tokens():
    """capacity 1 with all tokens routed to one expert: exactly one token
    gets expert output; the rest fall through with zero MLP contribution."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 6, 8).astype(np.float32))
    moe = SwitchMlp(num_experts=2, capacity_factor=0.17,  # cap = 1
                    dtype=jnp.float32)
    variables = moe.init(jax.random.PRNGKey(0), x)
    # force all tokens to expert 0 via a large router bias
    params = jax.tree_util.tree_map(lambda v: v, variables["params"])
    params["router"]["bias"] = jnp.asarray([100.0, -100.0])
    out = np.asarray(moe.apply({"params": params}, x))
    nonzero_tokens = (np.abs(out[0]).sum(-1) > 1e-6).sum()
    assert nonzero_tokens == 1  # one slot of capacity, rest dropped


@pytest.mark.heavy
def test_expert_sharded_matches_unsharded():
    """expert axis sharding is numerically invisible: same outputs with the
    stacked expert weights sharded over `expert` (+ data-sharded batch)."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        tree_param_shardings)
    mesh = _mesh(data=2, expert=4)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(4, 8, 16).astype(np.float32))
    plain = SwitchMlp(num_experts=4, dtype=jnp.float32)
    # pin the einsum formulation: auto now resolves to a2a on a sharded
    # expert axis, whose group-local capacity semantics differ (tested in
    # test_a2a_dispatch_matches_grouped_gather below)
    sharded = SwitchMlp(num_experts=4, dtype=jnp.float32, mesh=mesh,
                        dispatch="einsum")
    variables = plain.init(jax.random.PRNGKey(0), x)
    want = np.asarray(plain.apply(variables, x))

    shardings = tree_param_shardings(
        {"SwitchMlp_0": variables["params"]}, mesh)["SwitchMlp_0"]
    flat = {"/".join(str(p) for p in path): s for path, s in
            jax.tree_util.tree_flatten_with_path(shardings)[0]}
    assert any("expert" in str(s.spec) for n, s in flat.items() if "w1" in n)
    assert all("expert" not in str(s.spec)
               for n, s in flat.items() if "router" in n)

    sharded_params = jax.device_put(variables["params"], shardings)
    got = np.asarray(jax.jit(
        lambda p, x: sharded.apply({"params": p}, x))(sharded_params, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


@pytest.mark.heavy
def test_moe_vit_trains_with_aux_loss():
    """ViT + Switch MoE over mesh.expert trains through the Trainer; the
    sown load-balancing loss makes loss > cross_entropy (wd off)."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 2
    cfg.model.vit_heads = 2
    cfg.model.vit_num_experts = 4
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.mesh.data = 2
    cfg.mesh.expert = 4
    cfg.optimizer.weight_decay = 0.0
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
    # Switch aux loss is >= 1 by Cauchy-Schwarz (E·Σ f_e·p_e ≥ 1 for any
    # routing), so with wd=0 loss must exceed plain cross-entropy
    assert float(m["loss"]) > float(m["cross_entropy"])


def test_expert_axis_requires_moe_model():
    from distributed_resnet_tensorflow_tpu.train import Trainer
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.mesh.data = 2
    cfg.mesh.expert = 4
    with pytest.raises(ValueError, match="vit_num_experts"):
        Trainer(cfg)
    cfg.model.vit_num_experts = 6  # not divisible by 4
    with pytest.raises(ValueError, match="divisible"):
        Trainer(cfg)
    # MoE x tensor composes since round 5 (expert FFNs Megatron-split,
    # parallel/sharding.py): the Trainer must CONSTRUCT, not reject
    cfg2 = get_preset("smoke")
    cfg2.model.name = "vit"
    cfg2.model.vit_num_experts = 4
    cfg2.mesh.data = 4
    cfg2.mesh.tensor = 2
    Trainer(cfg2)


def test_top2_routing_combines_two_experts():
    """top_k=2: the output is the gate-weighted mix of BOTH selected
    experts' MLPs (checked against a direct per-token computation with
    ample capacity so nothing drops)."""
    import numpy as np
    from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 6, 16).astype(np.float32))
    m = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=4.0,
                  dtype=jnp.float32, top_k=2)
    variables = m.init(jax.random.PRNGKey(0), x)
    y, _ = m.apply(variables, x, mutable=["losses"])

    p = variables["params"]
    router_w = np.asarray(p["router"]["kernel"])
    router_b = np.asarray(p["router"]["bias"])
    w1, b1 = np.asarray(p["w1"]), np.asarray(p["bias1"])
    w2, b2 = np.asarray(p["w2"]), np.asarray(p["bias2"])
    xf = np.asarray(x).reshape(-1, 16)
    logits = xf @ router_w + router_b
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    order = np.argsort(-probs, axis=-1)
    e1, e2 = order[:, 0], order[:, 1]
    g1 = probs[np.arange(len(xf)), e1]
    g2 = probs[np.arange(len(xf)), e2]
    denom = g1 + g2

    # use jax for the exact gelu the module uses
    import flax.linen as fnn

    def mlp_jax(e, v):
        h = jnp.asarray(v) @ jnp.asarray(w1[e]) + jnp.asarray(b1[e])
        h = fnn.gelu(h)
        return np.asarray(h @ jnp.asarray(w2[e]) + jnp.asarray(b2[e]))

    want = np.stack([
        (g1[i] / denom[i]) * mlp_jax(e1[i], xf[i])
        + (g2[i] / denom[i]) * mlp_jax(e2[i], xf[i])
        for i in range(len(xf))])
    np.testing.assert_allclose(np.asarray(y).reshape(-1, 16), want,
                               rtol=2e-4, atol=2e-4)


def test_top2_capacity_priority_first_choice_wins():
    """First choices get capacity BEFORE any second choice: with capacity 1
    and a crafted router — token0 prefers A then B, token1 prefers B then A
    — each token must be served by its PRIMARY expert only (both backups
    find their expert full). If the waves were processed backups-first, the
    experts would swap (token0 ← B, token1 ← A), which this asserts against.
    """
    import numpy as np
    import flax.linen as fnn
    from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
    d = 8
    x = np.zeros((1, 2, d), np.float32)
    x[0, 0, 0] = 1.0   # token0
    x[0, 1, 1] = 1.0   # token1
    x = jnp.asarray(x)
    # capacity = ceil(top_k * N/E * cf) = ceil(2*2/2 * 0.5) = 1
    m = SwitchMlp(num_experts=2, mlp_ratio=2, capacity_factor=0.5,
                  dtype=jnp.float32, top_k=2)
    variables = m.init(jax.random.PRNGKey(0), x)
    p = jax.tree_util.tree_map(np.asarray, variables["params"])
    # router: token0 → logits (2, 1) (A first, B second);
    #         token1 → logits (1, 2) (B first, A second)
    rk = np.zeros((d, 2), np.float32)
    rk[0] = [2.0, 1.0]
    rk[1] = [1.0, 2.0]
    p["router"]["kernel"] = rk
    p["router"]["bias"] = np.zeros(2, np.float32)
    y, _ = m.apply({"params": jax.tree_util.tree_map(jnp.asarray, p)}, x,
                   mutable=["losses"])
    y = np.asarray(y)[0]

    def expert(e, v):
        h = np.asarray(fnn.gelu(
            jnp.asarray(v @ p["w1"][e] + p["bias1"][e])))
        return h @ p["w2"][e] + p["bias2"][e]

    # gates renormalize over the pair: max prob / (max + second) per token
    logits = np.asarray(x)[0] @ rk
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    g = probs.max(-1) / (probs[:, 0] + probs[:, 1])
    want0 = g[0] * expert(0, np.asarray(x)[0, 0])   # token0 ← A only
    want1 = g[1] * expert(1, np.asarray(x)[0, 1])   # token1 ← B only
    np.testing.assert_allclose(y[0], want0, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(y[1], want1, rtol=1e-5, atol=1e-6)
    # and NOT the swapped (backups-first) assignment
    swapped0 = g[0] * expert(1, np.asarray(x)[0, 0])
    assert not np.allclose(y[0], swapped0, atol=1e-4)


def test_top1_unchanged_by_top_k_field_default():
    """Default top_k=1 reproduces the original Switch behavior exactly."""
    import numpy as np
    from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(2, 4, 8).astype(np.float32))
    m1 = SwitchMlp(num_experts=2, mlp_ratio=2, dtype=jnp.float32)
    m2 = SwitchMlp(num_experts=2, mlp_ratio=2, dtype=jnp.float32, top_k=1)
    v = m1.init(jax.random.PRNGKey(0), x)
    y1, _ = m1.apply(v, x, mutable=["losses"])
    y2, _ = m2.apply(v, x, mutable=["losses"])
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))


@pytest.mark.heavy
def test_moe_top2_trains_through_trainer():
    import numpy as np
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 16
    cfg.model.vit_depth = 2
    cfg.model.vit_heads = 2
    cfg.model.vit_num_experts = 4
    cfg.model.vit_moe_top_k = 2
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.mesh.data = 2
    cfg.mesh.expert = 4
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow  # re-tiered out of the 870s tier-1 (ISSUE 13); runs in the full (unfiltered) suite with the other MoE-exactness slow tier
@pytest.mark.heavy
def test_gather_dispatch_matches_einsum():
    """The O(N+EC) gather dispatch == the one-hot einsum dispatch exactly
    (outputs AND gradients), for top-1 and top-2, with drops occurring."""
    import numpy as np
    from distributed_resnet_tensorflow_tpu.models.moe import SwitchMlp
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 8, 16).astype(np.float32))
    for top_k in (1, 2):
        for cf in (2.0, 0.5):  # ample capacity AND forced drops
            me = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                           dtype=jnp.float32, top_k=top_k, dispatch="einsum")
            mg = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                           dtype=jnp.float32, top_k=top_k, dispatch="gather")
            v = me.init(jax.random.PRNGKey(0), x)

            def loss(m):
                def fn(params, x):
                    y, _ = m.apply({"params": params}, x,
                                   mutable=["losses"])
                    return (y ** 2).sum()
                return fn

            le, ge = jax.value_and_grad(loss(me))(v["params"], x)
            lg, gg = jax.value_and_grad(loss(mg))(v["params"], x)
            assert np.isclose(float(le), float(lg), rtol=1e-5), (top_k, cf)
            for a, b in zip(jax.tree_util.tree_leaves(ge),
                            jax.tree_util.tree_leaves(gg)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5)


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_a2a_dispatch_matches_grouped_gather():
    """The hand-scheduled all-to-all dispatch (shard_map over
    data x expert, lax.all_to_all token exchange) == the pure-jit gather
    dispatch with capacity_groups = number of device sub-shards — outputs
    AND gradients, top-1 and top-2, with drops occurring. The groups are
    the a2a mode's exact semantics (GShard group-local capacity), so this
    is bit-level parity, not a statistical check."""
    mesh = _mesh(data=2, expert=4)
    rng = np.random.RandomState(4)
    # n_tokens = 4*16 = 64; shards = 2*4 = 8 -> n_sub = 8 tokens/device
    x = jnp.asarray(rng.randn(4, 16, 16).astype(np.float32))
    for top_k in (1, 2):
        for cf in (2.0, 0.5):  # ample capacity AND forced drops
            ref = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                            dtype=jnp.float32, top_k=top_k,
                            dispatch="gather", capacity_groups=8)
            a2a = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                            dtype=jnp.float32, top_k=top_k,
                            dispatch="a2a", mesh=mesh)
            v = ref.init(jax.random.PRNGKey(0), x)

            def loss(m):
                def fn(params, x):
                    y, _ = m.apply({"params": params}, x,
                                   mutable=["losses"])
                    return (y ** 2).sum()
                return fn

            lr_, gr = jax.value_and_grad(loss(ref))(v["params"], x)
            la, ga = jax.value_and_grad(loss(a2a))(v["params"], x)
            assert np.isclose(float(lr_), float(la), rtol=1e-5), (top_k, cf)
            for a, b in zip(jax.tree_util.tree_leaves(gr),
                            jax.tree_util.tree_leaves(ga)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-4, atol=1e-5)


def test_a2a_requires_expert_axis_and_divisibility():
    with pytest.raises(ValueError, match="mesh.expert"):
        m = SwitchMlp(num_experts=4, dtype=jnp.float32, dispatch="a2a")
        m.init(jax.random.PRNGKey(0), jnp.zeros((2, 4, 16)))
    mesh = _mesh(data=2, expert=4)
    with pytest.raises(ValueError, match="divisible"):
        m = SwitchMlp(num_experts=4, dtype=jnp.float32, dispatch="a2a",
                      mesh=mesh)
        # 2*7=14 tokens % 8 shards != 0
        m.init(jax.random.PRNGKey(0), jnp.zeros((2, 7, 16)))


@pytest.mark.heavy
def test_auto_dispatch_resolves_a2a_on_sharded_axis(monkeypatch):
    """auto -> a2a when tokens divide over the shards, einsum (no a2a
    call) otherwise — asserted by spying on the dispatch actually taken."""
    mesh = _mesh(data=2, expert=4)
    rng = np.random.RandomState(5)
    calls = []
    orig = SwitchMlp._a2a_dispatch

    def spy(self, *a, **k):
        calls.append("a2a")
        return orig(self, *a, **k)

    monkeypatch.setattr(SwitchMlp, "_a2a_dispatch", spy)
    for t, want_a2a in ((16, True), (7, False)):  # 2*7=14 tokens % 8 != 0
        calls.clear()
        x = jnp.asarray(rng.randn(2, t, 16).astype(np.float32))
        m = SwitchMlp(num_experts=4, dtype=jnp.float32, mesh=mesh)
        v = m.init(jax.random.PRNGKey(0), x)
        y = m.apply(v, x)
        assert y.shape == x.shape
        assert bool(jnp.isfinite(y).all())
        assert (len(calls) > 0) == want_a2a, (t, calls)


@pytest.mark.slow  # re-tiered out of the 870s tier-1; runs in the full (unfiltered) suite
@pytest.mark.heavy
def test_moe_tensor_parallel_matches_unsharded():
    """MoE x tensor (VERDICT r4 #4): each expert's FFN Megatron-split over
    `tensor` (w1/b1 columns, w2 rows + one psum — expert_ffn). a2a on
    dp=2 x ep=2 x tp=2 == the pure-jit gather reference with the matching
    group-local capacity (groups = dp x ep = 4; `tensor` doesn't change
    routing: tokens are replicated across it). Outputs AND grads, with
    drops occurring."""
    mesh = _mesh(data=2, expert=2, tensor=2)
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(4, 16, 16).astype(np.float32))
    for cf in (2.0, 0.5):
        ref = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                        dtype=jnp.float32, dispatch="gather",
                        capacity_groups=4)
        tp = SwitchMlp(num_experts=4, mlp_ratio=2, capacity_factor=cf,
                       dtype=jnp.float32, dispatch="a2a", mesh=mesh)
        v = ref.init(jax.random.PRNGKey(0), x)

        def loss(m):
            def fn(params, x):
                y, _ = m.apply({"params": params}, x, mutable=["losses"])
                return (y ** 2).sum()
            return fn

        lr_, gr = jax.value_and_grad(loss(ref))(v["params"], x)
        lt, gt = jax.value_and_grad(loss(tp))(v["params"], x)
        assert np.isclose(float(lr_), float(lt), rtol=1e-5), cf
        for a, b in zip(jax.tree_util.tree_leaves(gr),
                        jax.tree_util.tree_leaves(gt)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


def test_moe_tensor_param_sharding_rule():
    """The SwitchMlp sharding rule splits expert FFN weights over
    expert x tensor (and leaves router/bias2 tensor-replicated); with no
    expert axis the tensor split still applies; indivisible dims degrade
    to the expert-only placement."""
    from jax.sharding import PartitionSpec as P
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        param_sharding_rule)
    mesh = _mesh(data=2, expert=2, tensor=2)
    base = "['EncoderBlock_0']/['SwitchMlp_0']"
    assert param_sharding_rule(base + "/['w1']", (4, 16, 32), mesh) == \
        P("expert", None, "tensor")
    assert param_sharding_rule(base + "/['bias1']", (4, 32), mesh) == \
        P("expert", "tensor")
    assert param_sharding_rule(base + "/['w2']", (4, 32, 16), mesh) == \
        P("expert", "tensor", None)
    assert param_sharding_rule(base + "/['bias2']", (4, 16), mesh) == \
        P("expert", None)
    assert param_sharding_rule(base + "/['router']/['kernel']",
                               (16, 4), mesh) == P()
    # hidden dim not divisible by tensor -> expert split only
    assert param_sharding_rule(base + "/['w1']", (4, 16, 31), mesh) == \
        P("expert", None, None)
    # no expert axis: tensor still splits the FFN
    mesh_tp = _mesh(data=4, tensor=2)
    assert param_sharding_rule(base + "/['w1']", (4, 16, 32), mesh_tp) == \
        P(None, None, "tensor")


@pytest.mark.heavy
def test_moe_vit_trains_on_ep_x_tp_mesh():
    """ep x tp through the Trainer: the former blanket rejection is gone
    and a Switch-MoE ViT trains finitely on data=2 x expert=2 x tensor=2."""
    from distributed_resnet_tensorflow_tpu.data import (
        learnable_synthetic_iterator)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset
    cfg = get_preset("smoke")
    cfg.model.name = "vit"
    cfg.model.num_classes = 4
    cfg.model.compute_dtype = "float32"
    cfg.model.vit_dim = 32
    cfg.model.vit_depth = 2
    cfg.model.vit_heads = 2
    cfg.model.vit_num_experts = 2
    cfg.data.image_size = 8
    cfg.train.batch_size = 8
    cfg.mesh.data = 2
    cfg.mesh.expert = 2
    cfg.mesh.tensor = 2
    tr = Trainer(cfg)
    tr.init_state()
    state, m = tr.train(learnable_synthetic_iterator(8, 8, 4), num_steps=2)
    assert int(state.step) == 2
    assert np.isfinite(float(m["loss"]))
