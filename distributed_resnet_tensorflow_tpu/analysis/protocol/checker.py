"""Explicit-state model checker for the declared protocol specs.

BFS over EVERY interleaving of the abstract processes' enabled actions
(crashes, file losses and timeouts included — they are just actions) at
the spec's small-scope bounds. Stdlib-only, no devices, milliseconds per
protocol: the state spaces are hundreds to a few thousand states by
construction, and a model that outgrows ``state_cap`` is itself a
finding (the small-scope contract is part of the spec).

What gets checked:

  * **safety** — every invariant on every reachable state. A violation
    reports the SHORTEST action schedule from the initial state (BFS
    order), not just the bad state: the counterexample trace is the
    reviewable artifact (``fail → fail → zombie_revive``), anchored at
    the spec registration's file:line.
  * **liveness** — ``eventually`` goals via backward reachability on
    the explored graph (a reachable state from which the goal is
    UNREACHABLE is a livelock trap; the trace to the trap is the
    counterexample), ``reachable`` goals via plain forward reachability
    (the protocol can actually succeed at these bounds).

The committed artifact (``analysis/protocol_models.json``) records the
per-spec state/transition counts, the invariant inventory, the bounds
and a fingerprint over the sorted explored states+edges — sorted keys,
trailing newline, byte-identical across runs like
``collective_schedules.json``: its diff in review IS the protocol
change.
"""
from __future__ import annotations

import hashlib
import json
import os
from collections import deque
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..report import Finding
from .spec import Model, ProtocolSpec, load_specs

RULE_NAME = "protocol-model"

#: a declared-small-scope model must stay small; blowing this cap is a
#: spec bug (unbounded counter in the state), reported as a finding
STATE_CAP = 200_000


def _trace(parent: Dict[tuple, Optional[Tuple[tuple, str]]],
           state: tuple) -> List[str]:
    """Reconstruct the action schedule init -> state."""
    labels: List[str] = []
    cur: Optional[tuple] = state
    while cur is not None:
        link = parent[cur]
        if link is None:
            break
        cur, label = link
        labels.append(label)
    return labels[::-1]


def _trace_detail(parent, state: tuple) -> str:
    labels = _trace(parent, state)
    return ("schedule: " + (" -> ".join(labels) or "<initial state>")
            + f"\nfinal state: {state!r}")


def check_model(spec: ProtocolSpec,
                mutations: FrozenSet[str] = frozenset(),
                state_cap: int = STATE_CAP
                ) -> Tuple[List[Finding], dict]:
    """Exhaustively explore one spec's model; returns (findings, stats).

    ``mutations`` names guard-weakenings from ``spec.mutations`` — the
    seeded-bug legs tests use to prove the checker catches the class of
    bug each guard exists to prevent.
    """
    unknown = mutations - set(spec.mutations)
    if unknown:
        raise ValueError(f"{spec.name}: unknown mutation(s) "
                         f"{sorted(unknown)}; declared: {spec.mutations}")
    model: Model = spec.model(frozenset(mutations))
    findings: List[Finding] = []
    violated: set = set()   # invariant names already reported (shortest wins)

    parent: Dict[tuple, Optional[Tuple[tuple, str]]] = {model.init: None}
    edges: List[Tuple[tuple, str, tuple]] = []
    queue: deque = deque([model.init])

    def _check_safety(state: tuple) -> None:
        for name, inv in model.invariants:
            if name not in violated and not inv(state):
                violated.add(name)
                labels = _trace(parent, state)
                findings.append(Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: safety invariant '{name}' violated "
                    f"after {len(labels)} action(s): "
                    + (" -> ".join(labels) or "<initial state>"),
                    _trace_detail(parent, state)))

    _check_safety(model.init)
    truncated = False
    while queue:
        state = queue.popleft()
        nexts = sorted(model.actions(state), key=lambda a: (a[0], repr(a[1])))
        for label, s2 in nexts:
            edges.append((state, label, s2))
            if s2 not in parent:
                if len(parent) >= state_cap:
                    truncated = True
                    queue.clear()
                    break
                parent[s2] = (state, label)
                _check_safety(s2)
                queue.append(s2)
    if truncated:
        findings.append(Finding(
            RULE_NAME, spec.path, spec.line,
            f"{spec.name}: model exceeded the {state_cap}-state small-"
            f"scope cap — tighten the declared bounds {dict(spec.bounds)} "
            "(an unbounded counter in the state defeats exhaustive "
            "search)"))

    if not truncated:
        reachable = set(parent)
        for name, kind, goal in model.liveness:
            goal_states = {s for s in reachable if goal(s)}
            if kind == "reachable":
                if not goal_states:
                    findings.append(Finding(
                        RULE_NAME, spec.path, spec.line,
                        f"{spec.name}: liveness goal '{name}' is "
                        "UNREACHABLE at the declared bounds — the "
                        "protocol can never succeed in this model"))
                continue
            # 'eventually': backward closure of the goal set; any
            # reachable state outside it can never reach the goal again
            pred: Dict[tuple, List[tuple]] = {s: [] for s in reachable}
            for src, _, dst in edges:
                pred[dst].append(src)
            closure = set(goal_states)
            frontier = deque(goal_states)
            while frontier:
                s = frontier.popleft()
                for p in pred[s]:
                    if p not in closure:
                        closure.add(p)
                        frontier.append(p)
            traps = reachable - closure
            if traps:
                # report the BFS-shallowest trap (deterministic)
                trap = min(traps, key=lambda s: (len(_trace(parent, s)),
                                                 repr(s)))
                labels = _trace(parent, trap)
                findings.append(Finding(
                    RULE_NAME, spec.path, spec.line,
                    f"{spec.name}: liveness goal '{name}' has a trap — "
                    f"after {' -> '.join(labels) or '<initial state>'} "
                    "the goal is unreachable on every continuation",
                    _trace_detail(parent, trap)))

    digest = hashlib.sha256()
    for line in sorted(repr(s) for s in parent):
        digest.update(line.encode("utf-8"))
        digest.update(b"\n")
    for line in sorted(f"{s!r} --{label}--> {s2!r}"
                       for s, label, s2 in edges):
        digest.update(line.encode("utf-8"))
        digest.update(b"\n")
    stats = {
        "states": len(parent),
        "transitions": len(edges),
        "fingerprint": "sha256:" + digest.hexdigest(),
        "truncated": truncated,
    }
    return findings, stats


def run_protocol() -> Tuple[List[Finding], dict]:
    """The gate phase: check every registered spec (clean models, no
    mutations) and build the artifact document."""
    findings: List[Finding] = []
    specs_doc: Dict[str, dict] = {}
    for spec in load_specs():
        fs, stats = check_model(spec)
        findings += fs
        specs_doc[spec.name] = {
            "title": spec.title,
            "modules": list(spec.modules),
            "bounds": dict(spec.bounds),
            "safety": list(spec.safety_names()),
            "liveness": list(spec.liveness_names()),
            "mutations": list(spec.mutations),
            "declared_at": f"{spec.path}:{spec.line}",
            **stats,
        }
    return findings, {"schema_version": 1, "specs": specs_doc}


def artifact_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "protocol_models.json")


def write_artifact(doc: dict, path: Optional[str] = None) -> str:
    """Commit the model inventory — sorted keys, fixed layout, trailing
    newline: byte-identical across runs (the fingerprints make any
    model change a reviewable diff)."""
    if path is None:
        path = artifact_path()
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path
