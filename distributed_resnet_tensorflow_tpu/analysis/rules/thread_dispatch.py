"""cross-thread-dispatch: device-executing calls stay on dispatch threads.

The PR 2 incident, statically: only ONE thread per process may launch
multi-device XLA executions (the train-loop/consumer thread, or the serve
dispatch thread) — a second launcher interleaves per-device enqueue
orders and the next collective-bearing step deadlocks. The runtime
dispatch sanitizer (``analysis/dispatch_sanitizer.py``) catches this
live; this rule is its static complement over the thread-role registry
(``analysis/threads.py``):

  * every ``threading.Thread(target=...)`` spawn site (and executor
    ``submit`` of a package function) must resolve to a role in
    ``THREAD_ROLES`` — an unregistered spawn is a finding, which is what
    keeps the thread inventory (docs/static_analysis.md) honest;
  * from every spawn target whose role is NOT ``dispatch``, the rule
    walks the call graph; a reachable dispatch-bearing call (executing a
    ``jitted_*`` step, ``finalize_staged``/``StagedBatch.finalize`` —
    the compiled unpack) is a finding at that call site, naming the
    spawning thread.

Like all of hangcheck this under-approximates: callbacks and iterator
indirection contribute no edges (the staging worker's ``src`` iterator
is dynamic), so a clean pass is "no path the resolver can see" — the
runtime sanitizer remains the backstop.
"""
from __future__ import annotations

from typing import Iterable

from ..report import Finding
from .. import threads as threads_mod
from ..callgraph import get_callgraph

RULE_NAME = "cross-thread-dispatch"
DOC = __doc__


def check(ctx) -> Iterable[Finding]:
    graph = get_callgraph(ctx)
    for spawn in threads_mod.iter_spawn_sites(ctx):
        if spawn.target is None:
            if spawn.kind == "thread":
                yield Finding(
                    RULE_NAME, spawn.rel, spawn.lineno,
                    f"thread spawn with unresolvable target "
                    f"({spawn.target_desc}) — give the target a static "
                    "definition so its role can be registered in "
                    "analysis/threads.THREAD_ROLES")
            continue
        role = threads_mod.role_of(spawn.target)
        if role is None:
            yield Finding(
                RULE_NAME, spawn.rel, spawn.lineno,
                f"unregistered thread spawn target "
                f"{spawn.target.short()} — declare its role in "
                "analysis/threads.THREAD_ROLES (the thread-role "
                "inventory, docs/static_analysis.md)")
            continue
        if role == threads_mod.ROLE_DISPATCH:
            continue
        for key in sorted(graph.reachable([spawn.target.key])):
            fn = graph.funcs[key]
            for call in threads_mod.dispatch_bearing_calls(fn):
                yield Finding(
                    RULE_NAME, fn.rel, call.lineno,
                    f"multi-device dispatch reachable from the "
                    f"{role!r}-role thread spawned at "
                    f"{spawn.rel}:{spawn.lineno} "
                    f"(target {spawn.target.short()}) — only the "
                    "consumer/dispatch thread may execute compiled "
                    "programs (docs/input_pipeline.md threading model)")
