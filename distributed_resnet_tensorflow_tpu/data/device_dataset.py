"""Device-resident dataset — the whole dataset lives in HBM, batches are
gathered on device, the host ships only int32 indices.

Why: feeding the CIFAR flagship at TPU rate (~400 steps/s × 128 images) is
impossible for a one-core host pipeline, and even raw-uint8 streaming stalls
behind host→device transfers (measured: 95 steps/s streamed vs 414 device-
resident). CIFAR-scale data (150 MB uint8) is noise next to 16 GB HBM, so the
TPU-native design uploads the dataset once and the jitted step does

    batch = images[idx], labels[idx]        # on-device row gather, ~0.1 ms
    images = augment(batch, fold_in(key, step))   # ops/augment.py

leaving the host a 512-byte index transfer per step. The reference's
equivalent layer was the 16-thread host-side queue runner
(reference cifar_input.py:77-96) — hardware made this the better answer.

Epoch semantics match the host iterator (data/cifar.py): full-dataset
permutation per epoch, partial trailing batch dropped in train mode.
Single-process only (multi-host keeps the streamed per-shard path).
"""
from __future__ import annotations

from typing import Dict, Iterator

import numpy as np


def device_dataset_enabled(cfg, mode: str = "train") -> bool:
    """Resolve ``data.device_dataset`` (auto | on | off). Auto = on iff
    running on TPU, single process, CIFAR-scale dataset."""
    if mode != "train" or cfg.data.dataset not in ("cifar10", "cifar100"):
        return False
    setting = cfg.data.device_dataset
    if setting == "off":
        return False
    if setting not in ("auto", "on"):
        raise ValueError(f"unknown device_dataset setting {setting!r}")
    import jax
    if jax.process_count() > 1:
        if setting == "on":
            raise ValueError(
                "data.device_dataset=on requires a single process; "
                "multi-host training streams per-process shards instead")
        return False
    if setting == "on":
        return True
    return jax.default_backend() == "tpu"


def epoch_index_iterator(n: int, batch_size: int, seed: int = 0
                         ) -> Iterator[Dict[str, np.ndarray]]:
    """Index batches under full-epoch shuffle — the host half of the
    device-dataset path. Yields {"idx": (batch_size,) int32} forever."""
    if batch_size > n:
        raise ValueError(f"batch_size {batch_size} > dataset size {n}")
    rng = np.random.RandomState(seed)
    while True:
        perm = rng.permutation(n).astype(np.int32)
        for start in range(0, n - batch_size + 1, batch_size):
            yield {"idx": perm[start:start + batch_size]}
