"""Fault-tolerance subsystem: preemption, crash-consistent checkpoints,
NaN rollback, bounded retries, and the fault-injection harness.

The reference framework assumed a clean world — SLURM restarts on failure
and ``tf.train.Saver`` hopefully left something usable (SURVEY.md §2.14,
§4.4). At target scale (ImageNet in minutes over large meshes,
arXiv:1811.05233 / arXiv:1802.05799) preemptions, torn writes, and loss
blow-ups are the COMMON case; this package makes each one a handled,
tested code path. See docs/resilience.md for the protocols and the
launcher exit-code contract.
"""
from .manifest import (  # noqa: F401
    committed_steps, manifest_status, write_manifest)
from .preemption import (  # noqa: F401
    Preempted, PreemptionListener, RESUMABLE_EXIT_CODE)
from .retry import retry_call  # noqa: F401

# sentinel (and faultinject) are NOT re-exported eagerly: sentinel imports
# the train stack (and thus jax), and this package is imported by
# launch.py, which only needs the stdlib-light preemption constants —
# import from resilience.sentinel / resilience.faultinject directly.
