"""Standalone continuously-polling evaluator.

Capability parity with reference resnet_cifar_eval.py / resnet_imagenet_eval.py
(SURVEY.md §2.13): a separate process that (1) polls the checkpoint directory,
(2) restores the newest checkpoint, (3) evaluates ``eval_batch_count`` batches,
(4) tracks and reports best-so-far precision, tagging summaries with the
TRAINER's global step, (5) sleeps and repeats — or runs once with
``eval_once`` (reference resnet_cifar_eval.py:99-141).

The trainer↔evaluator interface is the checkpoint directory only, exactly as
in the reference (shared filesystem, separate SLURM node if desired).
"""
from __future__ import annotations

import logging
from typing import Dict, Iterator, Optional

from .checkpoint import CheckpointManager, poll_new_checkpoint
from .checkpoint.manager import CheckpointCorrupt
from .train.loop import Trainer
from .utils.metrics import MetricsWriter

log = logging.getLogger(__name__)


def make_eval_iterator(cfg, mesh=None):
    """Fresh eval iterator, sharded per BATCH slice so multi-host evaluation
    does one global pass (each distinct batch slice reads a disjoint set of
    files; processes replicating a slice — e.g. pipeline stages — read the
    same one). Without a mesh, falls back to process-index sharding (pure
    data-over-processes, where the two are identical)."""
    import jax

    from .data import create_input_iterator
    if mesh is not None:
        from .parallel.mesh import batch_slice_replicated, process_batch_slice
        shard_index, num_shards = process_batch_slice(mesh)
        replicated = batch_slice_replicated(mesh)
    else:
        shard_index, num_shards = jax.process_index(), jax.process_count()
        replicated = False
    return create_input_iterator(
        cfg, mode="eval", shard_index=shard_index, num_shards=num_shards,
        batch_size=max(1, cfg.data.eval_batch_size // num_shards),
        deterministic=replicated)


class Evaluator:
    def __init__(self, cfg, data_iter: Optional[Iterator] = None,
                 writer: Optional[MetricsWriter] = None):
        self.cfg = cfg
        self.trainer = Trainer(cfg)
        self.trainer.init_state()
        from .utils.config import resolve_checkpoint_dir, stacked_layout_stamp
        # writer=False: the evaluator READS a directory a live trainer may
        # be writing — it must not sweep the trainer's in-flight staging dir
        self.manager = CheckpointManager(
            resolve_checkpoint_dir(cfg), max_to_keep=1_000_000,
            layout_stamp=stacked_layout_stamp(cfg), writer=False)
        self.writer = writer
        self.best_precision = 0.0   # reference best_precision tracking
        self.last_step: Optional[int] = None
        # instance-level so the bound spans run() calls: the poller only
        # ever surfaces the NEWEST checkpoint, so "consecutive" failures
        # accrue one per poll over the evaluator's lifetime
        self.consecutive_failures = 0
        # a caller-supplied iterator is reused (must be infinite, e.g. the
        # CIFAR/synthetic generators); config-built iterators are rebuilt per
        # checkpoint because the ImageNet eval stream is one-pass
        self._data_iter = data_iter

    def _iter(self) -> Iterator:
        if self._data_iter is not None:
            return self._data_iter
        return make_eval_iterator(self.cfg, self.trainer.mesh)

    def evaluate_checkpoint(self, step: int) -> Dict[str, float]:
        """Restore a specific checkpoint + run eval_batch_count batches
        (reference ran 50 × bs=100, resnet_cifar_eval.py:111-122)."""
        from .telemetry.tracer import span
        with span("restore", step=step):
            self.trainer.state, _ = self.manager.restore(
                self.trainer.state, step)
        try:
            result = self.trainer.evaluate(self._iter(),
                                           self.cfg.eval.eval_batch_count)
        finally:
            # back to the unmonitored phase: between rounds the evaluator
            # legitimately makes no progress (checkpoint droughts), and a
            # parked "eval" phase would read as a hang to the watchdog
            if self.trainer.heartbeat is not None:
                self.trainer.heartbeat.set_phase("poll")
        self.best_precision = max(self.best_precision, result["precision"])
        result["best_precision"] = self.best_precision
        self.last_step = step
        if self.writer is not None:
            # summaries tagged by the trainer's global step, like the
            # reference (resnet_cifar_eval.py:125-133)
            self.writer.write_scalars(step, {
                "eval/precision": result["precision"],
                "eval/best_precision": self.best_precision,
                "eval/loss": result["loss"],
            })
        log.info("eval @ step %d: precision %.4f best %.4f loss %.4f",
                 step, result["precision"], self.best_precision,
                 result["loss"])
        return result

    def _wait_new_checkpoint(self, timeout_secs: float) -> Optional[int]:
        """Jittered-backoff poll over the non-blocking
        ``poll_new_checkpoint``: the first re-check comes ~1 s after a miss
        and the interval doubles up to ``eval.poll_interval_secs`` (±50%
        jitter per sleep). Replaces the fixed-interval busy-sleep —
        checkpoints published seconds apart are picked up in seconds
        instead of a full poll interval later, a drought backs off to the
        configured cadence, and many evaluators/serving replicas sharing a
        checkpoint filesystem don't stat it in lockstep. ``timeout_secs=0``
        keeps the single-poll contract."""
        import random
        import time
        cap = max(0.1, self.cfg.eval.poll_interval_secs)
        delay = min(1.0, cap)
        deadline = time.monotonic() + timeout_secs if timeout_secs else None
        rng = random.Random()
        while True:
            hit = poll_new_checkpoint(self.manager.directory, self.last_step)
            if hit is not None:
                return hit[0]
            if deadline is None or time.monotonic() >= deadline:
                return None
            time.sleep(min(delay * rng.uniform(0.5, 1.5),
                           max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, cap)

    def run(self, max_evals: Optional[int] = None,
            timeout_secs: float = 0.0) -> Dict[str, float]:
        """Poll-evaluate loop. ``eval_once`` (reference --eval_once flag) or
        ``max_evals`` bound it; otherwise runs until no new checkpoint appears
        within ``timeout_secs`` (0 = single pass over what exists).

        Damaged/vanished checkpoints are skipped, but only
        ``eval.max_consecutive_failures`` times IN A ROW (0 = unbounded):
        one torn step is the resilience layer doing its job; every step
        failing means the trainer side is persistently broken, and an
        evaluator spinning forever on it would hide that from the operator
        — exit nonzero instead."""
        result: Dict[str, float] = {}
        n = 0
        max_fail = self.cfg.eval.max_consecutive_failures
        while True:
            step = self._wait_new_checkpoint(timeout_secs)
            if step is None:
                log.info("no new checkpoint; evaluator exiting")
                return result
            try:
                result = self.evaluate_checkpoint(step)
            except (CheckpointCorrupt, FileNotFoundError) as e:
                # the step was damaged, quarantined by the trainer, or
                # reaped by retention between our poll and the restore —
                # a long-running evaluator skips it and keeps polling
                # rather than dying on exactly the damage the resilience
                # layer exists to survive (docs/resilience.md)
                self.consecutive_failures += 1
                log.warning("skipping checkpoint step %d (%d/%s consecutive "
                            "failures): %s", step, self.consecutive_failures,
                            max_fail or "unbounded", e)
                self.last_step = step
                if max_fail and self.consecutive_failures >= max_fail:
                    raise RuntimeError(
                        f"{self.consecutive_failures} consecutive "
                        f"checkpoints failed to evaluate (last: step {step}:"
                        f" {e}); the checkpoint stream looks persistently "
                        "broken — raise eval.max_consecutive_failures to "
                        "keep polling anyway") from e
                continue
            self.consecutive_failures = 0
            n += 1
            if self.cfg.eval.eval_once or (max_evals and n >= max_evals):
                return result
