"""Data echoing over a bounded decoded-sample host cache.

BENCH_r05 measured the regime this module exists for: the device trains
ImageNet RN50 at 2691 img/s while a single host core decodes ~220-350
JPEG img/s — the input-bound regime where "Massively Distributed SGD"
(arXiv:1811.05233) and the data-echoing literature show that REUSING
decoded samples buys real wall-clock: one JPEG decode feeds
``echo_factor`` training batches instead of one.

Mechanism (``echoing_iterator``): decoded samples stream into a bounded
pool of host uint8 crops (byte cap ``data.echo_cache_mb``; oldest-first
eviction when it overflows — the memory bound wins over echo
completeness, and every such eviction is counted). Whenever the pool
holds at least one batch worth of pending servings, a batch is emitted by
drawing DISTINCT samples via a seeded permutation — every emitted batch
is a fresh reshuffle of the cache, so echoed copies of a sample land in
different batches with different batchmates ("reshuffled per echo").
Each sample carries ``echo_factor`` total servings; exhausted samples
leave the pool. At stream end the pool drains through the same path, so
a finite stream under echo_factor=e yields each sample exactly e times
(modulo a trailing partial batch, logged — the no-silent-caps rule).

Echoed batches are raw host batches: they flow through the ordinary
threaded stacker → coalesced stager → device path, and the device-side
augmentation (ops/augment.py) draws fresh crops/flips per appearance —
which is what keeps echoed steps from being exact repeats. The
transfer-level analog (one H2D transfer feeding multiple steps) is
``data.echo_transfer`` in the train loop, not here.

Telemetry: emission busy time lands in ``utils.metrics.input_stages``
under the "echo" stage; hits/misses/evictions in
``utils.metrics.echo_stats`` (``{"event": "input_echo"}`` rows via
InputEchoHook; registered in EVENT_SCHEMAS).
"""
from __future__ import annotations

import logging
import time
from typing import Dict, Iterator, Optional

import numpy as np

log = logging.getLogger(__name__)


class _Entry:
    __slots__ = ("leaves", "uses", "served", "nbytes")

    def __init__(self, leaves: tuple, uses: int):
        self.leaves = leaves          # one row per batch key, copied
        self.uses = uses
        self.served = False
        self.nbytes = sum(getattr(v, "nbytes", 8) for v in leaves)


def echoing_iterator(src: Iterator[Dict[str, np.ndarray]],
                     echo_factor: int,
                     cache_mb: float = 256.0,
                     seed: int = 0,
                     stats=None) -> Iterator[Dict[str, np.ndarray]]:
    """Wrap a host batch iterator so each sample feeds ``echo_factor``
    batches (see module docstring). ``echo_factor <= 1`` returns ``src``
    unchanged. Deterministic: the same ``seed`` over the same source
    stream yields byte-identical echoed batches — the draw order is a
    seeded permutation, independent of wall-clock or thread timing.

    Closing the returned generator propagates close() to ``src`` (the
    worker-thread shutdown contract every input stage follows)."""
    if echo_factor <= 1:
        return src
    if stats is None:
        from ..utils.metrics import echo_stats
        stats = echo_stats
    cap = max(1, int(cache_mb * 1e6))
    stats.configure(echo_factor, cap)

    def gen():
        from ..telemetry.tracer import span
        from ..utils.metrics import input_stages
        rng = np.random.RandomState((seed * 1_000_003 + 12345) % (2 ** 32))
        # FIFO of _Entry: live entries are pool[head:] — eviction only
        # advances `head` (O(1)); the dead prefix is trimmed periodically
        # so a cap-bound stream never pays an O(pool) shift per eviction
        pool: list = []
        head = 0
        pool_bytes = 0
        pending_uses = 0          # sum of uses over the live pool
        keys: Optional[tuple] = None
        batch_size = 0
        # emission waits for the pool to reach this fill (derived from the
        # first sample's size: ~4 batches, capped by what the byte bound
        # can actually hold — a floor above the cap would never be reached
        # and the stream would block forever) so emitted batches MIX
        # samples across several source batches — greedy emission would
        # drain each source batch's uses before the next arrived and
        # "reshuffled" would degrade to within-batch permutation. The
        # end-of-stream drain ignores it.
        fill_entries: Optional[int] = None

        def emit():
            """One batch: distinct samples via a seeded permutation
            (duplicates only when the pool holds fewer distinct samples
            than a batch — a byte-capped pool or the drain tail)."""
            nonlocal pool, head, pool_bytes, pending_uses
            t0 = time.perf_counter()
            n = len(pool) - head
            if n >= batch_size:
                # distinct samples per batch (within-batch uniqueness)
                take = rng.permutation(n)[:batch_size]
            else:
                # pool smaller than a batch (byte-capped / tiny stream /
                # drain tail): draw from the multiset of remaining
                # servings so no entry is served past its uses — epoch
                # accounting stays exact (each sample emitted exactly
                # echo_factor times)
                avail = np.repeat(np.arange(n),
                                  [e.uses for e in pool[head:]])
                take = avail[rng.permutation(len(avail))[:batch_size]]
            hits = 0
            rows = []
            exhausted = False
            for i in take:
                e = pool[head + i]
                if e.served:
                    hits += 1
                e.served = True
                e.uses -= 1
                pending_uses -= 1
                exhausted = exhausted or e.uses <= 0
                rows.append(e.leaves)
            out = {k: np.stack([r[ki] for r in rows])
                   for ki, k in enumerate(keys)}
            if exhausted:
                kept = []
                for e in pool[head:]:
                    if e.uses > 0:
                        kept.append(e)
                    else:
                        pool_bytes -= e.nbytes
                pool = kept
                head = 0
            nbytes = sum(v.nbytes for v in out.values())
            input_stages.add("echo", time.perf_counter() - t0,
                             items=batch_size, nbytes=nbytes)
            stats.add(emitted=batch_size, hits=hits, cache_bytes=pool_bytes)
            return out

        try:
            for batch in src:
                if keys is None:
                    keys = tuple(sorted(batch))
                    batch_size = int(np.shape(batch[keys[0]])[0])
                with span("input.echo"):
                    for i in range(batch_size):
                        entry = _Entry(
                            tuple(np.array(batch[k][i]) for k in keys),
                            echo_factor)
                        pool.append(entry)
                        pool_bytes += entry.nbytes
                        pending_uses += echo_factor
                        evic = lost = 0
                        while pool_bytes > cap and len(pool) - head > 1:
                            old = pool[head]
                            head += 1
                            pool_bytes -= old.nbytes
                            pending_uses -= old.uses
                            evic += 1
                            lost += old.uses
                        if evic:
                            stats.add(evictions=evic, lost_uses=lost,
                                      cache_bytes=pool_bytes)
                    if head and head >= max(256, batch_size):
                        del pool[:head]  # trim the dead prefix, amortized
                        head = 0
                    stats.add(decoded=batch_size, cache_bytes=pool_bytes)
                    if fill_entries is None and pool:
                        per_entry = max(1, pool[head].nbytes)
                        max_live = max(1, int(cap // per_entry))
                        if max_live * echo_factor < batch_size:
                            # the cap can never accumulate one batch worth
                            # of servings: emission would block forever —
                            # fail loudly instead of hanging the train loop
                            raise ValueError(
                                f"data.echo_cache_mb={cache_mb:g} holds "
                                f"only ~{max_live} decoded sample(s) "
                                f"(~{per_entry} B each); with echo_factor="
                                f"{echo_factor} that can never fill a "
                                f"batch of {batch_size} — raise "
                                "echo_cache_mb or lower the batch size")
                        fill_entries = min(4 * batch_size, max_live)
                while pending_uses >= batch_size and \
                        len(pool) - head >= fill_entries:
                    yield emit()
            # stream end: drain the pool through the same path (full
            # batches only — a partial batch cannot be dispatched)
            while pending_uses >= batch_size and len(pool) - head > 0:
                yield emit()
            if pending_uses:
                log.warning(
                    "echoing_iterator: dropping %d trailing echo "
                    "serving(s) at stream end (smaller than one batch of "
                    "%d)", pending_uses, batch_size)
        finally:
            close = getattr(src, "close", None)
            if close is not None:
                try:
                    close()
                except ValueError:  # generator running on another thread
                    pass

    return gen()
