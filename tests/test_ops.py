"""Tests for ops/ — GroupedBatchNorm semantics (cross-replica vs the
reference's per-replica BN, SURVEY.md §7 'hard parts')."""
import jax
import jax.numpy as jnp
import numpy as np

from distributed_resnet_tensorflow_tpu.ops import GroupedBatchNorm


def _apply(model, x, train=True):
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    if train:
        y, mut = model.apply(variables, x, train=True, mutable=["batch_stats"])
        return y, variables, mut["batch_stats"]
    return model.apply(variables, x, train=False), variables, None


def test_global_bn_normalizes():
    x = jnp.asarray(np.random.RandomState(0).randn(16, 4, 4, 8) * 3 + 5,
                    jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32, groups=1)
    y, _, _ = _apply(model, x)
    assert np.allclose(np.asarray(y).mean((0, 1, 2)), 0, atol=1e-4)
    assert np.allclose(np.asarray(y).std((0, 1, 2)), 1, atol=1e-2)


def test_grouped_bn_equals_per_shard_bn():
    """groups=G must reproduce running BN independently on each shard —
    the reference's per-replica semantics (reference README.md:38,54)."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(8, 4, 4, 3).astype(np.float32))
    grouped = GroupedBatchNorm(dtype=jnp.float32, groups=2)
    y, _, _ = _apply(grouped, x)

    single = GroupedBatchNorm(dtype=jnp.float32, groups=1)
    y0, _, _ = _apply(single, x[:4])
    y1, _, _ = _apply(single, x[4:])
    np.testing.assert_allclose(np.asarray(y),
                               np.concatenate([np.asarray(y0), np.asarray(y1)]),
                               rtol=1e-5, atol=1e-5)


def test_grouped_bn_running_stats_are_global():
    """Running stats must aggregate over ALL groups (law of total variance)
    so the evaluator sees one consistent moment set."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(32, 2, 2, 4).astype(np.float32) * 2 + 1)
    g = GroupedBatchNorm(dtype=jnp.float32, groups=4, momentum=0.0)
    _, _, stats = _apply(g, x)
    want_mean = np.asarray(x).mean((0, 1, 2))
    want_var = np.asarray(x).var((0, 1, 2))
    np.testing.assert_allclose(np.asarray(stats["mean"]), want_mean, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]), want_var, atol=1e-4)


def test_eval_uses_running_stats():
    x = jnp.ones((4, 2, 2, 3), jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    y = model.apply(variables, x, train=False)
    # fresh stats: mean 0 var 1 → y ≈ x
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-3)


def test_indivisible_groups_raise():
    import pytest
    x = jnp.ones((6, 2, 2, 3), jnp.float32)
    model = GroupedBatchNorm(dtype=jnp.float32, groups=4)
    with pytest.raises(ValueError):
        model.init(jax.random.PRNGKey(0), x, train=True)


def test_mesh_axis_zero_collapses():
    """MeshConfig axis 0 == collapsed (docstring contract)."""
    from distributed_resnet_tensorflow_tpu.parallel import resolve_axis_sizes
    from distributed_resnet_tensorflow_tpu.utils.config import MeshConfig
    sizes = resolve_axis_sizes(MeshConfig(data=-1, tensor=0), 8)
    assert sizes == (1, 8, 1, 1, 1, 1)


def test_stat_subsample_matches_band_moments():
    """stat_subsample=s must normalize with EXACTLY the moments of the
    center band of H/s rows (and store them as running stats)."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(8, 6, 6, 4).astype(np.float32) * 2 + 3)
    m = GroupedBatchNorm(dtype=jnp.float32, stat_subsample=2, momentum=0.0)
    y, _, stats = _apply(m, x)
    xs = np.asarray(x)[:, 1:4, :, :]  # h=6, band=3 rows, lo=(6-3)//2=1
    want_mean = xs.mean((0, 1, 2))
    want_var = xs.var((0, 1, 2))
    np.testing.assert_allclose(np.asarray(stats["mean"]), want_mean,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats["var"]), want_var, atol=1e-5)
    want_y = (np.asarray(x) - want_mean) / np.sqrt(want_var + 1e-5)
    np.testing.assert_allclose(np.asarray(y), want_y, rtol=1e-4, atol=1e-4)


def test_stat_subsample_close_to_exact_and_grouped():
    """On iid data the band estimate tracks the exact moments (large-sample
    sanity: the training-numerics drift is the estimator variance),
    including under groups>1."""
    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(32, 16, 16, 8).astype(np.float32) * 1.7 - 0.4)
    y_exact, _, _ = _apply(GroupedBatchNorm(dtype=jnp.float32), x)
    y_sub, _, _ = _apply(
        GroupedBatchNorm(dtype=jnp.float32, stat_subsample=2), x)
    np.testing.assert_allclose(np.asarray(y_sub), np.asarray(y_exact),
                               rtol=0.1, atol=0.05)
    yg_exact, _, _ = _apply(GroupedBatchNorm(dtype=jnp.float32, groups=2), x)
    yg_sub, _, _ = _apply(
        GroupedBatchNorm(dtype=jnp.float32, groups=2, stat_subsample=2), x)
    np.testing.assert_allclose(np.asarray(yg_sub), np.asarray(yg_exact),
                               rtol=0.1, atol=0.08)


def test_band_stat_bn_gradients_are_exact():
    """Autodiff of the band-stat forward == the analytic BN gradient with
    band-restricted through-stats terms: dx_j = a·(dy_j − 1_band(j)·(dβ +
    x̂_j·dγ)/|band|), dγ = Σ_all dy·x̂, dβ = Σ_all dy."""
    rng = np.random.RandomState(6)
    x = jnp.asarray(rng.randn(4, 8, 6, 5).astype(np.float32) * 1.5 + 0.7)
    scale = jnp.asarray(rng.rand(5).astype(np.float32) + 0.5)
    bias = jnp.asarray(rng.randn(5).astype(np.float32))
    w = jnp.asarray(rng.randn(*x.shape).astype(np.float32))  # loss weights
    eps, sub = 1e-5, 2
    h = x.shape[1]
    bh = h // sub
    lo = (h - bh) // 2

    def fwd(x, s, b):
        xs = x[:, lo:lo + bh]
        mean = jnp.mean(xs, axis=(0, 1, 2))
        var = jnp.mean(jnp.square(xs), axis=(0, 1, 2)) - jnp.square(mean)
        return ((x - mean) * jax.lax.rsqrt(var + eps)) * s + b

    gx, gs, gb = jax.grad(lambda *a: jnp.sum(fwd(*a) * w),
                          argnums=(0, 1, 2))(x, scale, bias)
    # analytic
    xs = np.asarray(x)[:, lo:lo + bh]
    mean = xs.mean((0, 1, 2))
    var = xs.var((0, 1, 2))
    inv = 1.0 / np.sqrt(var + eps)
    xhat = (np.asarray(x) - mean) * inv
    dy = np.asarray(w)
    dbeta = dy.sum((0, 1, 2))
    dgamma = (dy * xhat).sum((0, 1, 2))
    n = x.shape[0] * bh * x.shape[2]
    corr = (dbeta + xhat * dgamma) / n
    band = np.zeros((1, h, 1, 1)); band[:, lo:lo + bh] = 1.0
    want_dx = np.asarray(scale) * inv * (dy - band * corr)
    np.testing.assert_allclose(np.asarray(gs), dgamma, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gb), dbeta, rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gx), want_dx, rtol=2e-4, atol=2e-4)


def test_stat_subsample_ignored_on_2d():
    """(N, C) inputs have no spatial lattice — subsample must be a no-op."""
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    y1, _, _ = _apply(GroupedBatchNorm(dtype=jnp.float32), x)
    y2, _, _ = _apply(GroupedBatchNorm(dtype=jnp.float32, stat_subsample=4), x)
    np.testing.assert_allclose(np.asarray(y2), np.asarray(y1), atol=1e-6)
