"""Pallas kernel tests (interpret mode on CPU; the same kernels compile for
TPU where bench.py exercises them)."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_resnet_tensorflow_tpu.ops.pallas import (
    flash_attention, softmax_xent)
from distributed_resnet_tensorflow_tpu.ops.attention import attention


def test_softmax_xent_matches_optax():
    rng = np.random.RandomState(0)
    logits = jnp.asarray(rng.randn(37, 10).astype(np.float32))  # odd B, C
    labels = jnp.asarray(rng.randint(0, 10, 37))
    got = softmax_xent(logits, labels, True)
    want = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_softmax_xent_imagenet_classes():
    """1001 classes (non-128-multiple) — wrapper pads lanes."""
    rng = np.random.RandomState(1)
    logits = jnp.asarray(rng.randn(8, 1001).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 1001, 8))
    got = softmax_xent(logits, labels, True)
    want = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_softmax_xent_grad_matches_optax():
    rng = np.random.RandomState(2)
    logits = jnp.asarray(rng.randn(16, 12).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 12, 16))

    g1 = jax.grad(lambda l: softmax_xent(l, labels, True).mean())(logits)
    g2 = jax.grad(lambda l: optax.softmax_cross_entropy_with_integer_labels(
        l, labels).mean())(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-6)


def test_flash_attention_matches_dense():
    rng = np.random.RandomState(3)
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, False, True)
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_causal_matches_dense():
    rng = np.random.RandomState(4)
    q, k, v = (jnp.asarray(rng.randn(1, 64, 2, 8).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, True, True)
    want = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_unaligned_seq():
    """T=100 (not a block multiple) exercises the padded/masked path."""
    rng = np.random.RandomState(5)
    q, k, v = (jnp.asarray(rng.randn(1, 100, 1, 8).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, False, True)
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_grad_matches_dense():
    rng = np.random.RandomState(6)
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32))
               for _ in range(3))

    g1 = jax.grad(lambda q: flash_attention(q, k, v, False, True).sum())(q)
    g2 = jax.grad(lambda q: attention(q, k, v).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_flash_attention_fused_bwd_all_grads_match_dense():
    """The fused Pallas backward (dq + dk/dv kernels) against dense-attention
    autodiff, for all three inputs at once."""
    rng = np.random.RandomState(9)
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))
               for _ in range(3))
    w = jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))  # cotangent mix

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, False, True) * w).sum()

    def loss_dense(q, k, v):
        return (attention(q, k, v) * w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_attention_fused_bwd_causal_padded():
    """Causal + unaligned T (valid_len mask + padded q rows) through the
    fused backward."""
    rng = np.random.RandomState(10)
    q, k, v = (jnp.asarray(rng.randn(1, 100, 2, 8).astype(np.float32))
               for _ in range(3))

    g_flash = jax.grad(
        lambda q, k, v: flash_attention(q, k, v, True, True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(
        lambda q, k, v: attention(q, k, v, causal=True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_flash_attention_bwd_multiblock():
    """T=300 spans multiple q AND k blocks: accumulation across the
    sequential grid dimension in both backward kernels."""
    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.randn(1, 300, 1, 8).astype(np.float32))
               for _ in range(3))
    g_flash = jax.grad(
        lambda q, k, v: flash_attention(q, k, v, False, True).sum(),
        argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(
        lambda q, k, v: attention(q, k, v).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_flash_attention_padded_masked_path():
    """t=300 > block 256 and not a multiple: exercises the valid_len mask."""
    rng = np.random.RandomState(7)
    q, k, v = (jnp.asarray(rng.randn(1, 300, 1, 8).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, False, True)
    want = attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_padded_causal():
    rng = np.random.RandomState(8)
    q, k, v = (jnp.asarray(rng.randn(1, 300, 1, 8).astype(np.float32))
               for _ in range(3))
    got = flash_attention(q, k, v, True, True)
    want = attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
