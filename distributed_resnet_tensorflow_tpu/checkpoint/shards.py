"""Per-host sharded checkpoint payloads — stage only what you own.

The single-payload (orbax) layout makes every save a whole-state write and
forces multi-process saves to be SYNCHRONOUS (orbax barriers its sharded
write with collectives, which may not run on a writer thread). With the
ZeRO-1 optimizer sharding (parallel/sharding.py) the state is no longer
even fully addressable per host — so checkpointing follows the layout:

  * every host's writer thread serializes ONLY the array pieces its own
    devices own (the ZeRO-1 optimizer shard, fsdp param shards) into
    ``shards/host-<p>.bin`` + a JSON index, fsyncs them, and drops a
    ``.done-<p>`` marker;
  * the chief additionally writes the replicated leaves once
    (``shards/base.bin``) and finalizes by WAITING ON MARKER FILES — no
    collectives off the main thread — before the usual manifest + atomic
    commit rename (resilience/manifest.py);
  * restore merges every index in the committed dir and reassembles each
    leaf from byte-range pieces, so the reader needs no knowledge of the
    writer's host count: save at N processes, restore at M, re-sharding
    into whatever layout the live state's rule table resolved.

The piece format is deliberately dumb: raw ``tobytes()`` payloads at
recorded offsets with dtype/shape/start in the index (bfloat16 round-trips
via ml_dtypes' registered numpy dtype). Integrity is the manifest's job —
every file here lands in MANIFEST.json's size+SHA-256 list like any other
payload file.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from ..analysis.protocol.spec import Model, ProtocolSpec, register_spec

SHARDS_DIR = "shards"
SHARD_FORMAT = 1
_DONE_PREFIX = ".done-"


def leaf_key(path) -> str:
    """Canonical flat key of one state leaf (jax keystr) — the join key
    between a live state's flattened tree and the shard indexes."""
    return jax.tree_util.keystr(path)


def _path_components(path) -> List[dict]:
    """JSON-able path encoding, enough to rebuild DICT subtrees (the
    serving hot-swap reads params/batch_stats this way); NamedTuple /
    sequence components are recorded but only dicts are rebuildable."""
    out = []
    for p in path:
        if hasattr(p, "key"):
            out.append({"k": str(p.key)})
        elif hasattr(p, "idx"):
            out.append({"i": int(p.idx)})
        elif hasattr(p, "name"):
            out.append({"a": str(p.name)})
        else:
            out.append({"r": str(p)})
    return out


def _piece_start(index, shape) -> Tuple[int, ...]:
    """Normalized start offsets of one shard index (tuple of slices)."""
    return tuple(int(s.start or 0) for s in index) if index else ()


def _owned_pieces(arr: jax.Array) -> List[Tuple[Tuple[int, ...], Any]]:
    """[(start_offsets, device_data)] for the array pieces THIS process
    owns. Ownership of a (possibly replicated) piece goes to the lowest
    device id holding it, so the union across processes covers the array
    exactly once — no host writes bytes another host already owns."""
    by_idx: Dict[Tuple, Any] = {}
    owner: Dict[Tuple, Any] = {}
    for shard in arr.addressable_shards:
        key = _piece_start(shard.index, arr.shape)
        if key not in by_idx:
            by_idx[key] = shard
    for dev, index in arr.sharding.devices_indices_map(arr.shape).items():
        key = _piece_start(index, arr.shape)
        cur = owner.get(key)
        if cur is None or dev.id < cur.id:
            owner[key] = dev
    pidx = jax.process_index()
    return [(key, shard.data) for key, shard in sorted(by_idx.items())
            if owner[key].process_index == pidx]


class SnapshotParts:
    """One host's view of a state snapshot, split by destination file:
    ``base`` (replicated leaves, chief-written) and ``owned`` (this
    host's pieces of sharded leaves). All payloads are host numpy by the
    time the writer thread sees this — the loop thread materializes."""

    __slots__ = ("base", "owned")

    def __init__(self, base, owned):
        self.base = base      # [(key, path_components, np.ndarray)]
        self.owned = owned    # [(key, path_components, global_shape,
        #                        dtype_str, [(start, np.ndarray)])]


def host_snapshot_parts(tree) -> SnapshotParts:
    """Device→host snapshot of ``tree`` for the sharded layout. Like
    ``manager._host_snapshot`` the D2H copies are ISSUED asynchronously
    first (one overlapped transfer) and then materialized — the
    loop-thread cost an async save pays. Must run on the loop thread:
    the caller is about to donate these buffers to the next step.

    Only the CHIEF collects the replicated (``base``) leaves — they are
    chief-written (``write_host_shards``), and a peer snapshotting the
    full replicated params tree per save would charge real D2H wall to
    the goodput ``checkpoint`` bucket for bytes it immediately drops."""
    chief = jax.process_index() == 0
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    plan = []
    for path, leaf in flat:
        if isinstance(leaf, jax.Array) and not leaf.sharding.is_fully_replicated:
            plan.append((path, leaf, _owned_pieces(leaf)))
        elif chief:
            plan.append((path, leaf, None))
    # pass 1: issue every copy
    for _path, leaf, pieces in plan:
        targets = [p for _s, p in pieces] if pieces is not None else \
            ([leaf] if isinstance(leaf, jax.Array) else [])
        for t in targets:
            try:
                t.copy_to_host_async()
            except Exception:
                break
    # pass 2: materialize
    base, owned = [], []
    for path, leaf, pieces in plan:
        key = leaf_key(path)
        comps = _path_components(path)
        if pieces is None:
            base.append((key, comps, np.asarray(leaf)))
        else:
            owned.append((key, comps, tuple(int(d) for d in leaf.shape),
                          str(np.dtype(leaf.dtype)),
                          [(start, np.asarray(data))
                           for start, data in pieces]))
    return SnapshotParts(base, owned)


def _write_pieces(shards_dir: str, stem: str, leaves) -> Tuple[int, int]:
    """Write one ``<stem>.bin`` + ``<stem>.json`` pair; ``leaves`` is
    [(key, comps, global_shape, dtype, [(start, np.ndarray)])]. Returns
    (payload_bytes, files_written). Both files are fsynced — durability
    before the marker/manifest says so."""
    os.makedirs(shards_dir, exist_ok=True)
    bin_path = os.path.join(shards_dir, stem + ".bin")
    index: List[dict] = []
    nbytes = 0
    with open(bin_path, "wb") as f:
        for key, comps, gshape, dtype, pieces in leaves:
            rec = {"key": key, "path": comps, "shape": list(gshape),
                   "dtype": dtype, "pieces": []}
            for start, arr in pieces:
                arr = np.ascontiguousarray(arr)
                off = f.tell()
                data = arr.tobytes()
                f.write(data)
                rec["pieces"].append({
                    "offset": off, "nbytes": len(data),
                    "start": list(start), "shape": list(arr.shape)})
                nbytes += len(data)
            index.append(rec)
        f.flush()
        os.fsync(f.fileno())
    json_path = os.path.join(shards_dir, stem + ".json")
    with open(json_path, "w") as f:
        json.dump({"format": SHARD_FORMAT,
                   "process_count": jax.process_count(),
                   "leaves": index}, f)
        f.flush()
        os.fsync(f.fileno())
    return nbytes, 2


def write_host_shards(staging_dir: str, process_index: int,
                      parts: SnapshotParts) -> Tuple[int, int]:
    """This host's contribution: its owned pieces of every sharded leaf
    (``host-<p>``) and, on the chief, the replicated base leaves
    (``base``). Returns (payload_bytes, files)."""
    shards_dir = os.path.join(staging_dir, SHARDS_DIR)
    total_b = total_f = 0
    if parts.owned:
        b, n = _write_pieces(shards_dir, f"host-{process_index:05d}",
                             parts.owned)
        total_b += b
        total_f += n
    if process_index == 0:
        b, n = _write_pieces(shards_dir, "base", [
            (key, comps, tuple(arr.shape), str(np.dtype(arr.dtype)),
             [((0,) * arr.ndim, arr)])
            for key, comps, arr in parts.base])
        total_b += b
        total_f += n
    return total_b, total_f


def write_done_marker(staging_dir: str, process_index: int) -> None:
    """Durable witness that this host's shard files are fully staged —
    the ONLY coordination primitive of the multi-process finalize (plain
    files on the shared directory; no collectives off the main thread)."""
    shards_dir = os.path.join(staging_dir, SHARDS_DIR)
    os.makedirs(shards_dir, exist_ok=True)
    path = os.path.join(shards_dir, f"{_DONE_PREFIX}{process_index:05d}")
    with open(path, "w") as f:
        f.write("ok")
        f.flush()
        os.fsync(f.fileno())


def done_markers(staging_dir: str) -> set:
    """Process indices whose done markers are visible."""
    shards_dir = os.path.join(staging_dir, SHARDS_DIR)
    out = set()
    try:
        names = os.listdir(shards_dir)
    except OSError:
        return out
    for name in names:
        if name.startswith(_DONE_PREFIX):
            try:
                out.add(int(name[len(_DONE_PREFIX):]))
            except ValueError:
                continue
    return out


# ---------------------------------------------------------------------------
# read side
# ---------------------------------------------------------------------------

def is_sharded_layout(step_dir: str) -> bool:
    """True when ``step_dir`` holds a per-host sharded payload."""
    shards_dir = os.path.join(step_dir, SHARDS_DIR)
    try:
        return any(n.endswith(".json") for n in os.listdir(shards_dir))
    except OSError:
        return False


class ShardReader:
    """Merged view of every index file in one committed step dir; leaves
    assemble from byte-range pieces regardless of how many hosts wrote
    them — the cross-host-count restore path."""

    def __init__(self, step_dir: str):
        self.shards_dir = os.path.join(step_dir, SHARDS_DIR)
        self._leaves: Dict[str, dict] = {}
        self._handles: Dict[str, Any] = {}
        for name in sorted(os.listdir(self.shards_dir)):
            if not name.endswith(".json"):
                continue
            with open(os.path.join(self.shards_dir, name)) as f:
                idx = json.load(f)
            stem = name[:-len(".json")]
            for rec in idx.get("leaves", []):
                cur = self._leaves.setdefault(rec["key"], {
                    "shape": tuple(rec["shape"]),
                    "dtype": rec["dtype"],
                    "path": rec.get("path", []),
                    "pieces": []})
                if cur["shape"] != tuple(rec["shape"]) or \
                        cur["dtype"] != rec["dtype"]:
                    raise ValueError(
                        f"shard indexes disagree about leaf {rec['key']!r}")
                for piece in rec["pieces"]:
                    cur["pieces"].append((stem, piece))

    def close(self) -> None:
        for f in self._handles.values():
            f.close()
        self._handles.clear()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def keys(self) -> set:
        return set(self._leaves)

    def _read(self, stem: str, offset: int, nbytes: int) -> bytes:
        f = self._handles.get(stem)
        if f is None:
            f = self._handles[stem] = open(
                os.path.join(self.shards_dir, stem + ".bin"), "rb")
        f.seek(offset)
        data = f.read(nbytes)
        if len(data) != nbytes:
            raise ValueError(
                f"short read in {stem}.bin ({len(data)}/{nbytes} bytes)")
        return data

    def assemble(self, key: str) -> np.ndarray:
        """Reassemble one leaf from every piece any host wrote. Raises if
        the pieces do not cover the full array — a torn or mixed-step
        shard set must fail the restore (the caller falls back to an
        older committed checkpoint), never silently zero-fill."""
        meta = self._leaves[key]
        shape, dtype = meta["shape"], np.dtype(meta["dtype"])
        if shape == ():
            stem, piece = meta["pieces"][0]
            return np.frombuffer(
                self._read(stem, piece["offset"], piece["nbytes"]),
                dtype=dtype).reshape(())[()]
        out = np.empty(shape, dtype)
        covered = 0
        seen = set()
        for stem, piece in meta["pieces"]:
            start = tuple(piece["start"])
            pshape = tuple(piece["shape"])
            if (start, pshape) in seen:
                continue  # duplicated piece (replicated writers)
            seen.add((start, pshape))
            arr = np.frombuffer(
                self._read(stem, piece["offset"], piece["nbytes"]),
                dtype=dtype).reshape(pshape)
            sel = tuple(slice(s, s + d) for s, d in zip(start, pshape))
            out[sel] = arr
            covered += arr.size
        if covered < int(np.prod(shape, dtype=np.int64)):
            raise ValueError(
                f"leaf {key!r} pieces cover {covered} of "
                f"{int(np.prod(shape))} elements — torn shard set")
        return out

    def read_subtree(self, root: str) -> Any:
        """Rebuild the nested-DICT subtree rooted at top-level key
        ``root`` (e.g. "params", "batch_stats") as host numpy — the
        serving hot-swap's restore path (serve/swap.py). Only dict path
        components exist under those roots by construction."""
        out: dict = {}
        found = False
        for key, meta in self._leaves.items():
            comps = meta["path"]
            if not comps or comps[0] != {"k": root}:
                continue
            found = True
            cur = out
            for c in comps[1:-1]:
                if "k" not in c:
                    raise ValueError(
                        f"non-dict path component {c} under {root!r}")
            for c in comps[1:-1]:
                cur = cur.setdefault(c["k"], {})
            if len(comps) == 1:
                return self.assemble(key)
            cur[comps[-1]["k"]] = self.assemble(key)
        if not found and root not in ("batch_stats",):
            raise KeyError(f"no leaves under {root!r} in shard indexes")
        return out


# ---------------------------------------------------------------------------
# declared protocol model (analysis/protocol/, docs/static_analysis.md)
# ---------------------------------------------------------------------------

def _ckpt_commit_model(mutations):
    """Crash-consistent sharded commit, 3 hosts, one step: every host
    stages its shard then drops a ``.done-`` marker; the chief waits for
    ALL markers before the manifest write + atomic staging->final rename
    (checkpoint/manager.py ``_write_sharded``), or raises on the
    finalize deadline — a torn step must never become visible.

    State: ``(host_phases, markers, chief, committed, reader)`` —
    ``host_phases[i]`` in idle/staged/marked/crashed, ``markers[i]``
    whether host i's done marker is on disk, ``chief`` in wait/renamed/
    aborted, ``reader`` what a poll-side consumer observed (None until
    it opens the step; committed_steps only ever surfaces renamed
    steps, so the reader action is gated on ``committed``).
    """
    n_hosts = 3

    def actions(s):
        ph, mk, chief, committed, reader = s
        out = []
        for i in range(n_hosts):
            if ph[i] == "idle":
                out.append((f"stage({i})",
                            (ph[:i] + ("staged",) + ph[i + 1:],
                             mk, chief, committed, reader)))
            if ph[i] == "staged":
                out.append((f"mark({i})",
                            (ph[:i] + ("marked",) + ph[i + 1:],
                             mk[:i] + (True,) + mk[i + 1:],
                             chief, committed, reader)))
            if i != 0 and ph[i] in ("idle", "staged"):
                # a SIGKILL before the marker: the shard may be torn
                out.append((f"crash({i})",
                            (ph[:i] + ("crashed",) + ph[i + 1:],
                             mk, chief, committed, reader)))
        if chief == "wait" and ph[0] == "marked":
            if all(mk) or "skip_marker_wait" in mutations:
                out.append(("finalize_rename",
                            (ph, mk, "renamed", True, reader)))
            if not all(mk) and any(p == "crashed" for p in ph):
                # finalize deadline expires -> manager RAISES; the step
                # is abandoned in staging/, never renamed
                out.append(("finalize_timeout",
                            (ph, mk, "aborted", committed, reader)))
        if committed and reader is None:
            out.append(("reader_open",
                        (ph, mk, chief, committed,
                         f"step@{sum(mk)}/{n_hosts}")))
        return out

    def _committed_means_complete(s):
        ph, mk, chief, committed, reader = s
        return not committed or all(mk)

    def _reader_never_torn(s):
        reader = s[4]
        return reader is None or reader == f"step@{n_hosts}/{n_hosts}"

    return Model(
        init=(("idle",) * n_hosts, (False,) * n_hosts,
              "wait", False, None),
        actions=actions,
        invariants=(
            ("committed_step_has_all_done_markers",
             _committed_means_complete),
            ("reader_never_observes_uncommitted_shards",
             _reader_never_torn),
        ),
        liveness=(
            ("chief_finalize_terminates", "eventually",
             lambda s: s[2] != "wait"),
            ("commit_can_succeed", "reachable",
             lambda s: s[3]),
        ),
    )


CKPT_COMMIT_PROTOCOL = register_spec(ProtocolSpec(
    name="ckpt-sharded-commit",
    title="crash-consistent sharded checkpoint commit: stage, per-host "
          ".done- markers, chief finalize barrier, atomic rename",
    modules=("distributed_resnet_tensorflow_tpu/checkpoint/shards.py",
             "distributed_resnet_tensorflow_tpu/checkpoint/manager.py"),
    bounds={"hosts": 3, "steps": 1},
    model=_ckpt_commit_model,
    mutations=("skip_marker_wait",),
    event_edges={"ckpt_shard": {}},
    literals={
        "shards": "SHARDS_DIR — per-step shard payload directory",
        ".done-": "_DONE_PREFIX — per-host staging-complete marker",
        "host-": "per-host shard file stem",
    },
))
