"""Vision Transformer — the attention-based model family.

The reference is conv-only; this framework treats attention and long context
as first-class (ops/attention.py, ops/pallas/flash_attention.py). This module
provides the trainable model that exercises those ops end-to-end through the
same Trainer/config path as the ResNets:

  * ``VisionTransformer`` — patchify → encoder stack → mean-pool → head,
    drop-in for the classification pipeline (same (B, H, W, C) → logits
    contract as the ResNets).
  * ``attention_impl`` selects the kernel: "dense" (reference semantics),
    "blockwise" (O(T) memory lax), or "flash" (Pallas TPU kernel).

All linear algebra is MXU-shaped (model dims multiples of 128 recommended);
bf16 compute / f32 params as elsewhere.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp


def _apply_attention(q, k, v, impl: str):
    if impl == "dense":
        from ..ops.attention import attention
        return attention(q, k, v)
    if impl == "blockwise":
        from ..ops.attention import blockwise_attention
        return blockwise_attention(q, k, v)
    if impl == "flash":
        from ..ops.pallas import flash_attention
        return flash_attention(q, k, v)
    raise ValueError(f"unknown attention_impl {impl!r}")


class MultiHeadAttention(nn.Module):
    num_heads: int
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        if d % self.num_heads:
            raise ValueError(f"dim {d} not divisible by heads {self.num_heads}")
        hd = d // self.num_heads
        qkv = nn.Dense(3 * d, use_bias=False, dtype=self.dtype,
                       name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, t, self.num_heads, hd)
        k = k.reshape(b, t, self.num_heads, hd)
        v = v.reshape(b, t, self.num_heads, hd)
        out = _apply_attention(q, k, v, self.attention_impl)
        out = out.reshape(b, t, d)
        return nn.Dense(d, use_bias=False, dtype=self.dtype, name="proj")(out)


class EncoderBlock(nn.Module):
    num_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        d = x.shape[-1]
        h = nn.LayerNorm(dtype=self.dtype)(x)
        x = x + MultiHeadAttention(self.num_heads, self.dtype,
                                   self.attention_impl)(h)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * d, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(d, dtype=self.dtype)(h)
        return x + h


class VisionTransformer(nn.Module):
    num_classes: int = 10
    patch_size: int = 4
    dim: int = 128
    depth: int = 6
    num_heads: int = 4
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attention_impl: str = "dense"
    remat: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, train: bool = True) -> jax.Array:
        del train  # no BN; deterministic (dropout-free baseline config)
        b, h, w, c = x.shape
        p = self.patch_size
        if h % p or w % p:
            raise ValueError(f"image {h}x{w} not divisible by patch {p}")
        x = x.astype(self.dtype)
        # patchify: conv with stride p == linear patch embed
        x = nn.Conv(self.dim, (p, p), strides=(p, p), padding="VALID",
                    dtype=self.dtype, name="patch_embed")(x)
        x = x.reshape(b, -1, self.dim)
        t = x.shape[1]
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, t, self.dim), jnp.float32)
        x = x + pos.astype(self.dtype)
        block = EncoderBlock
        if self.remat:
            block = nn.remat(block)
        for _ in range(self.depth):
            x = block(self.num_heads, self.mlp_ratio, self.dtype,
                      self.attention_impl)(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x.mean(axis=1).astype(jnp.float32)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
