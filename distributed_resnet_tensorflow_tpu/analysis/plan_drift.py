"""The ``plan-drift`` gate phase: re-check the what-if planner's
predictions and commit the diffable ``analysis/plan_catalog.json``.

Runs inside ``main.py check`` after hangcheck-schedule (which supplies
the freshly traced signatures). Three jobs:

  1. Re-cost every committed (layout, variant) candidate of the
     PLAN_PRESETS with the planner's baked-in REFERENCE constants
     (telemetry/planner.py) — fully deterministic, so the artifact this
     writes is byte-identical across runs and machines. A perf-relevant
     change (new collective, different wire bytes, a model change)
     shows up as a reviewable catalog diff next to the schedule diff.
  2. Sanity-findings on the model itself: every prediction finite and
     positive, every planned preset ranked with a recommendation —
     a catalog that silently lost a preset is a red gate, not a smaller
     file.
  3. Cross-check the fabric's MEASURED bandwidth catalog
     (results/bandwidth/<fabric>.json) against a live micro-probe on
     the virtual-8 mesh: one replicated psum, timed. A catalog claiming
     bandwidth off by more than ``PROBE_SANITY_FACTOR`` in either
     direction is a finding — the seeded-corruption contract
     (tests/test_planner.py): a bandwidth-table lie must fail the gate,
     because every live drift sentinel on this fabric inherits it.

Only three presets are costed (one per model family, including the MoE
member the acceptance bar names) — the phase must fit the analysis
gate's 300s budget next to lint/elaborate/hangcheck, and the other
presets' schedules are already byte-covered by the schedule artifact.
"""
from __future__ import annotations

import logging
import math
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .report import Finding

log = logging.getLogger(__name__)

RULE = "plan-drift"

#: presets the committed catalog covers: one ResNet/CIFAR, one
#: ResNet/ImageNet, one ViT-MoE (the vit/moe family the acceptance bar
#: requires) — a bounded, representative slice of the schedule artifact
PLAN_PRESETS = ("cifar10_resnet50", "imagenet_resnet50", "vit_moe")

#: a measured-catalog bandwidth may differ from the gate's micro-probe
#: by machine load / hardware generation, but not by this factor: wide
#: enough for any honest CPU/TPU spread, narrow enough that a corrupted
#: table (the 1e15 B/s lie) cannot hide
PROBE_SANITY_FACTOR = 100.0


def _micro_probe_bytes_per_sec(n_devices: int = 8,
                               payload_mb: float = 4.0,
                               reps: int = 3) -> Optional[float]:
    """Achieved bytes/sec of one replicated psum over every mesh axis —
    the cheapest honest bandwidth sample this process can take. None
    when the mesh cannot build (the cross-check degrades to skipped,
    not red: the catalog may outlive the machine that can probe it)."""
    import time as _time
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax import lax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from ..parallel.mesh import shard_map_compat
        if jax.device_count() < n_devices:
            return None
        devices = np.array(jax.devices()[:n_devices]).reshape(n_devices)
        mesh = Mesh(devices, ("data",))
        elems = max(1, int(payload_mb * 1e6) // 4)

        def _psum(x):
            return lax.psum(x, ("data",))

        fn = jax.jit(shard_map_compat(
            _psum, mesh, in_specs=P(), out_specs=P()))
        # deliberate direct put: the micro-probe times ONE replicated
        # psum on a throwaway mesh inside the analysis gate — routing
        # through parallel/sharding's stager would drag the training
        # transfer plumbing into a standalone diagnostic
        x = jax.device_put(jnp.zeros((elems,), jnp.float32),  # shardcheck: ok(stray-device-put)
                           NamedSharding(mesh, P()))
        jax.block_until_ready(fn(x))  # compile + warm
        best = None
        for _ in range(max(1, reps)):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(x))
            dt = _time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return (elems * 4) / best if best and best > 0 else None
    except Exception as e:
        log.warning("plan-drift micro-probe unavailable (%s); bandwidth "
                    "catalog cross-check skipped", e)
        return None


def check_bandwidth_catalog(probe_bps: Optional[float] = None
                            ) -> List[Finding]:
    """Findings for a measured catalog that contradicts a live
    micro-probe beyond PROBE_SANITY_FACTOR. Silent when no catalog
    exists for this fabric (a fresh checkout has nothing to lie)."""
    from ..telemetry import bandwidth
    doc = bandwidth.load_catalog()
    if not doc:
        return []
    if probe_bps is None:
        probe_bps = _micro_probe_bytes_per_sec()
    if not probe_bps or probe_bps <= 0:
        return []
    findings: List[Finding] = []
    path = bandwidth.catalog_path(doc.get("fabric"))
    for sig in sorted(doc.get("axes", {})):
        bps = float(doc["axes"][sig].get("bytes_per_sec", 0.0))
        if bps <= 0 or not math.isfinite(bps):
            findings.append(Finding(
                RULE, path, 0,
                f"bandwidth catalog axes[{sig!r}]: non-positive/non-"
                f"finite bytes_per_sec {bps!r}"))
            continue
        ratio = bps / probe_bps
        if ratio > PROBE_SANITY_FACTOR or ratio < 1.0 / PROBE_SANITY_FACTOR:
            findings.append(Finding(
                RULE, path, 0,
                f"bandwidth catalog axes[{sig!r}] claims "
                f"{bps:.3g} B/s but a live micro-probe measured "
                f"{probe_bps:.3g} B/s (ratio {ratio:.3g}, tolerance "
                f"×{PROBE_SANITY_FACTOR:g}) — stale or corrupted "
                f"catalog; delete or re-probe it (docs/planner.md)"))
    return findings


#: canonical host-factorization for the committed tuned_comm rows: the
#: virtual-8 gate mesh factored 2 hosts × 4 devices (the same k the
#: hangcheck overlap+hier families pin)
TUNE_CANONICAL_K = 4


def _tuned_comm(preset: str, signatures: Dict[str, dict],
                table) -> Optional[dict]:
    """Re-run the startup autotune's chooser (planner.tune_comm_plan)
    against the committed overlap plan of one preset, on the reference
    table. Deterministic by construction; on the tier-row-less reference
    table the chooser documents its flat fallback in the committed row —
    exactly the drift sentinel: a chooser change, a new candidate grid,
    or a plan-bytes change diffs here in review."""
    from ..telemetry import planner
    key = f"{preset}@dp/overlap"
    if key not in signatures:
        cands = sorted(k for k in signatures
                       if k.startswith(preset + "@") and
                       k.endswith("/overlap"))
        if not cands:
            return None
        key = cands[0]
    plan = signatures[key].get("plan") or {}
    sizes = [int(b) for b in plan.get("bucket_bytes") or []]
    if not sizes:
        return None
    declared = plan.get("declared_collectives") or []
    axes = []
    for ops in declared:
        first = ops[0] if ops else "psum@data"
        sig = first.split("@", 1)[-1].split("[", 1)[0]
        axes.append(sig)
    while len(axes) < len(sizes):
        axes.append("data")
    snap = {
        "grad_bytes": sum(sizes),
        "bucket_bytes": sizes,
        "bucket_reduce_axes": axes[:len(sizes)],
        "compress": plan.get("compress", "off"),
    }
    # configured cap = the CommConfig default (4 MB) — every committed
    # preset leaves comm.bucket_mb at the default
    tuned = planner.tune_comm_plan(
        snap, table, intra_k=TUNE_CANONICAL_K, bucket_mb=4.0)
    tuned["schedule_key"] = key
    return tuned


def build_catalog(signatures: Dict[str, dict],
                  presets: Sequence[str] = PLAN_PRESETS,
                  n_devices: int = 8) -> Tuple[List[Finding], dict]:
    """(findings, catalog document). The document embeds the reference
    constants it was computed with, so a constant change diffs loudly
    in review instead of silently re-baselining every number."""
    from ..telemetry import planner

    findings: List[Finding] = []
    plans: Dict[str, dict] = {}
    table = planner.BandwidthTable.reference()
    for preset in presets:
        if not any(k.startswith(preset + "@") for k in signatures):
            findings.append(Finding(
                RULE, preset, 0,
                f"planned preset {preset!r} has no committed collective "
                "schedules — the hangcheck-schedule phase must trace it "
                "first"))
            continue
        plan = planner.plan_for_preset(preset, signatures,
                                       n_devices=n_devices,
                                       bandwidth=table)
        for key, cand in sorted(plan["candidates"].items()):
            for field in ("step_secs", "compute_secs", "comm_secs",
                          "comm_exposed_secs"):
                v = cand.get(field)
                if v is None or not math.isfinite(v) or v < 0 or \
                        (field in ("step_secs", "compute_secs") and v == 0):
                    findings.append(Finding(
                        RULE, f"{preset}:{key}", 0,
                        f"degenerate prediction {field}={v!r} — the "
                        "cost model lost an input (schedule bytes, "
                        "FLOPs table, or bandwidth row)"))
        if not plan.get("recommended"):
            findings.append(Finding(
                RULE, preset, 0,
                "no recommended layout — every candidate failed to "
                "cost"))
        plans[preset] = {
            "candidates": plan["candidates"],
            "ranked": plan["ranked"],
            "recommended": plan["recommended"],
        }
        tuned = _tuned_comm(preset, signatures, table)
        if tuned is not None:
            plans[preset]["tuned_comm"] = tuned
            if not math.isfinite(tuned["predicted_secs"]) or \
                    tuned["predicted_secs"] < 0:
                findings.append(Finding(
                    RULE, preset, 0,
                    f"degenerate tuned_comm prediction "
                    f"{tuned['predicted_secs']!r} — tune_comm_plan lost "
                    "an input (bucket bytes or bandwidth row)"))
    doc = {
        "schema_version": 1,
        "devices": n_devices,
        "reference": {
            "bytes_per_sec": planner.REFERENCE_BYTES_PER_SEC,
            "latency_secs": planner.REFERENCE_LATENCY_SECS,
            "peak_tflops": planner.REFERENCE_PEAK_TFLOPS,
            "assumed_mfu": planner.ASSUMED_MFU,
            "overlap_efficiency": planner.OVERLAP_EFFICIENCY,
            "train_flops_multiplier": planner.TRAIN_FLOPS_MULTIPLIER,
            "act_flops_per_byte": planner.ACT_FLOPS_PER_BYTE,
            "tune_bucket_mb": list(planner.TUNE_BUCKET_MB),
            "tune_sanity_factor": planner.TUNE_SANITY_FACTOR,
            "tune_canonical_k": TUNE_CANONICAL_K,
        },
        "plans": plans,
    }
    return findings, doc


def run_plan_drift(signatures: Optional[Dict[str, dict]] = None,
                   n_devices: int = 8,
                   probe_bps: Optional[float] = None
                   ) -> Tuple[List[Finding], dict]:
    """The whole phase: catalog build + model sanity + bandwidth-catalog
    cross-check. ``signatures`` defaults to the committed schedule
    artifact (the check CLI passes the freshly traced map so the
    catalog matches what the same run just committed)."""
    from ..telemetry.comm_report import load_schedules
    if signatures is None:
        signatures = load_schedules()
    findings, doc = build_catalog(signatures, n_devices=n_devices)
    findings += check_bandwidth_catalog(probe_bps=probe_bps)
    return findings, doc


def write_plan_catalog(doc: dict, path: Optional[str] = None) -> str:
    """Commit the catalog — sorted keys, fixed layout, trailing newline,
    atomic replace: byte-identical across runs whenever the predictions
    are (which build_catalog's determinism guarantees)."""
    import json
    if path is None:
        path = plan_catalog_path()
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def plan_catalog_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "plan_catalog.json")
