"""Generate a structured, learnable dataset in ImageNet TFRecord format.

No-egress stand-in for the real ImageNet shards (same role as
make_synth_cifar.py for the CIFAR path): JPEG-encoded tf.train.Examples in
train-{i:05d}-of-{N} / validation-{i:05d}-of-{M} shards with the exact
feature schema the reference's record_parser consumed
(reference resnet_imagenet_main.py:115-136: image/encoded +
image/class/label, labels 1-based with 0 = background).

Learnability must survive the VGG train augmentation (random resize of the
shorter side to [256,512] → random 224² crop → flip → constant-mean
subtraction, reference vgg_preprocessing.py:284-314). Scale-coded textures
(spatial frequency) do NOT survive the 2× random rescale, so the class
signal here is geometry-free: each class adds a class-specific RGB direction
(points on a color circle) on top of shared low-frequency clutter and heavy
pixel noise. Mean color is invariant to resize/crop/flip, and VGG
preprocessing subtracts fixed channel means — per-image statistics pass
through — so the signal reaches the network intact while still requiring
learning through the noise (and through JPEG compression).

Image sizes are drawn from realistic ImageNet-ish dimensions so the decode
and resize cost of benchmarking against these shards matches the real
pipeline's work profile.

Usage: python tools/make_synth_imagenet.py out_dir [--classes 16]
           [--train-per-class 128] [--val-per-class 16] [--shards 8]
"""
from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from distributed_resnet_tensorflow_tpu.data.preprocessing import encode_jpeg
from distributed_resnet_tensorflow_tpu.data.tfrecord import (
    build_example, write_tfrecords)

# ImageNet-ish source dimensions (h, w) to draw from — mix of landscape,
# portrait and square so the aspect-preserving resize path is exercised
SOURCE_DIMS = [(375, 500), (500, 375), (333, 500), (500, 500),
               (400, 300), (300, 400), (480, 640), (256, 256)]


def class_color(cls: int, num_classes: int) -> np.ndarray:
    """Unit RGB direction for a class: points on a color circle in the
    plane orthogonal to luminance (so classes differ in hue, not
    brightness — JPEG preserves hue well at quality 90)."""
    theta = 2 * np.pi * cls / num_classes
    u = np.asarray([1.0, -0.5, -0.5]) / np.sqrt(1.5)   # R vs GB
    v = np.asarray([0.0, 1.0, -1.0]) / np.sqrt(2.0)    # G vs B
    return np.cos(theta) * u + np.sin(theta) * v


def make_image(cls: int, num_classes: int,
               rng: np.random.RandomState) -> np.ndarray:
    h, w = SOURCE_DIMS[rng.randint(len(SOURCE_DIMS))]
    # shared clutter: a few random low-frequency gratings (class-independent)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    clutter = np.zeros((h, w), np.float32)
    for _ in range(3):
        fy, fx = rng.uniform(-0.02, 0.02, 2)
        clutter += np.cos(2 * np.pi * (fy * yy + fx * xx)
                          + rng.uniform(0, 2 * np.pi))
    img = 118.0 + 20.0 * clutter[..., None] * rng.uniform(0.5, 1.0, 3)
    img = img + 26.0 * class_color(cls, num_classes)       # the signal
    img = img + rng.normal(0, 30.0, (h, w, 3))             # pixel noise
    return np.clip(img, 0, 255).astype(np.uint8)


def write_split(out_dir: str, prefix: str, num_shards: int, total_shards: int,
                num_classes: int, per_class: int, seed: int) -> None:
    rng = np.random.RandomState(seed)
    # labels are 1-based (0 = background) like the reference's shards
    labels = np.repeat(np.arange(1, num_classes + 1), per_class)
    rng.shuffle(labels)
    shards = np.array_split(labels, num_shards)
    for i, shard_labels in enumerate(shards):
        recs = []
        for label in shard_labels:
            img = make_image(int(label) - 1, num_classes, rng)
            recs.append(build_example({
                "image/encoded": [encode_jpeg(img)],
                "image/class/label": [int(label)],
            }))
        name = f"{prefix}-{i:05d}-of-{total_shards:05d}"
        write_tfrecords(os.path.join(out_dir, name), recs)
        print(f"wrote {name} ({len(recs)} examples)")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--train-per-class", type=int, default=128)
    ap.add_argument("--val-per-class", type=int, default=16)
    ap.add_argument("--shards", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)
    write_split(args.out_dir, "train", args.shards, args.shards,
                args.classes, args.train_per_class, args.seed)
    write_split(args.out_dir, "validation", max(1, args.shards // 4),
                max(1, args.shards // 4),
                args.classes, args.val_per_class, args.seed + 1)


if __name__ == "__main__":
    main()
