"""Local multi-process launcher test — real 2-process SPMD over a loopback
coordinator (successor of the reference's submit_mac_dist.sh smoke cluster,
SURVEY.md §4.1)."""
import socket
import sys

import pytest

from distributed_resnet_tensorflow_tpu.launch import launch_local


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_spmd_train(tmp_path):
    rc = launch_local(
        num_processes=2,
        devices_per_process=8,  # explicit: 2 procs × 8 fake devices
        main_args=[
            "--preset", "smoke",
            "--set", "model.name=logistic",
            "--set", "model.input_size=192",   # 8*8*3
            "--set", "model.num_classes=10",
            "--set", "data.image_size=8",
            "--set", "train.batch_size=16",  # 2 procs × 8 fake devices
            "--set", "train.train_steps=6",
            "--set", "train.steps_per_loop=2",  # covers make_global_stacked_batch
            "--set", "train.log_every_steps=2",
            "--set", f"log_root={tmp_path}",
            "--set", "checkpoint.save_every_steps=0",
            "--set", "checkpoint.save_every_secs=0",
        ],
        port=_free_port())
    assert rc == 0
