"""Mixture-of-Experts MLP (Switch top-1 / GShard-style top-2 routing) — the
consumer of the ``expert`` mesh axis.

The reference is a dense-only trainer (SURVEY.md §2.10); this completes the
6-axis mesh so every axis has a model consumer. Design (Switch Transformer
recipe, scoped to what the ViT family needs):

  * E expert MLPs with stacked parameters (E, D, F)/(E, F, D), sharded over
    the ``expert`` axis by parallel/sharding.py's rule — each device group
    holds E/expert_axis experts (and their optimizer moments).
  * Top-1 (Switch) or top-2 (GShard-style) routing with probability gating
    and a fixed per-expert capacity ``ceil(top_k · tokens/E ·
    capacity_factor)``; over-capacity tokens fall through on the residual
    path. Top-2 normalizes the two gates over the selected pair and gives
    first choices capacity priority over second choices (the GShard
    ordering: a token's backup never displaces another token's primary).
  * Two dispatch formulations, selected by ``dispatch``:
      - "einsum": one-hot (N, E, C) dispatch/combine einsums — GSPMD
        partitions them over the sharded expert dimension and inserts the
        token-exchange collectives (the sharding-first formulation; no
        hand-written all-to-all). Cost: the one-hot tensors are O(N·E·C)
        HBM — measured 2.46× a dense MLP step at 8k tokens × 8 experts
        (docs/moe_r3.json).
      - "gather": scatter the kept token ids into an (E·C,) slot table,
        gather expert inputs by slot, gather combines back per token —
        O(N + E·C) memory, no one-hot tensors at all.
      - "a2a": hand-scheduled expert parallelism (round 4, VERDICT r3 #3).
        ``shard_map`` over (data, fsdp, expert): the token dim is split
        along the expert axis too (free — the enclosing model replicates
        activations over ``expert``), each device runs the O(N+EC) gather
        dispatch on its N/(dp·ep) tokens, ONE ``lax.all_to_all`` along
        ``expert`` swaps token chunks for expert chunks, the expert MLP
        runs on (E/ep, ep·C_sub, D), and a reverse all-to-all + local
        combine return. vs the einsum form this (a) moves O(cf·N_sub·D)
        per device instead of all-reducing the full (E, C, D) buffer and
        (b) does NOT replicate expert FLOPs across the data axis.
        Capacity semantics are GShard *group-local* (one group per device
        sub-shard) rather than the global cumsum of the other two modes;
        ``capacity_groups`` on the gather path is the pure-jit reference
        of exactly these semantics, and the two are exact-parity tested
        on the fake mesh (tests/test_moe.py).
    "auto" uses gather when the expert dim is NOT mesh-sharded (scatters
    across a sharded dim would make GSPMD all-gather the slot table) and
    a2a when it is, falling back to einsum if the token count doesn't
    divide over (data × fsdp × expert).
  * The Switch load-balancing auxiliary loss (E · Σ_e fraction_e · prob_e)
    is sown into the ``losses`` collection; the train step adds every sown
    loss scaled by ``model.moe_aux_weight`` (train/loop.py).
"""
from __future__ import annotations

import math
from functools import partial
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


def _route_assign(flat_probs: jax.Array, num_experts: int, capacity: int,
                  top_k: int):
    """Routing waves + capacity queueing over one token group.

    ``flat_probs`` (N, E) → list of (expert_idx, gate, pos, keep) per wave.
    Top-2 renormalizes the selected pair's gates and queues second choices
    BEHIND every first choice (GShard priority: a token's backup never
    displaces another token's primary). Position ``pos`` is the token's
    queue slot in its expert; ``pos >= capacity`` drops the assignment
    (gate zeroed). Pure function of the probs block so the jit-level
    (global group) and shard_map-level (device-local group) dispatches
    share one implementation and vmap gives the grouped reference."""
    e = num_experts
    expert_idx = jnp.argmax(flat_probs, axis=-1)
    gate1 = jnp.max(flat_probs, axis=-1)
    onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
    if top_k == 2:
        # second choice: argmax with the first masked out (probs ∈ [0,1]:
        # -2 always loses); gates renormalized over the selected pair
        masked = flat_probs - onehot * 2.0
        expert_idx2 = jnp.argmax(masked, axis=-1)
        gate2 = jnp.take_along_axis(
            flat_probs, expert_idx2[:, None], axis=-1)[:, 0]
        denom = gate1 + gate2
        waves = [(expert_idx, gate1 / denom), (expert_idx2, gate2 / denom)]
    else:
        waves = [(expert_idx, gate1)]

    assigned = []                      # (idx, gate, pos, keep) per wave
    base_counts = jnp.zeros((e,), jnp.float32)
    for idx_k, gate_k in waves:
        oh = jax.nn.one_hot(idx_k, e, dtype=jnp.float32)     # (N, E)
        pos_in_expert = (jnp.cumsum(oh, axis=0) - 1.0) * oh  # (N, E)
        pos = (jnp.sum(pos_in_expert, axis=-1)
               + oh @ base_counts).astype(jnp.int32)         # (N,)
        keep = pos < capacity
        assigned.append((idx_k, gate_k * keep.astype(jnp.float32),
                         pos, keep))
        base_counts = base_counts + oh.sum(axis=0)
    return assigned


def switch_aux_loss(flat_probs: jax.Array) -> jax.Array:
    """Switch load-balancing loss E·Σ_e fraction_e·mean_prob_e over one
    token group (first-choice fractions)."""
    e = flat_probs.shape[-1]
    onehot = jax.nn.one_hot(jnp.argmax(flat_probs, -1), e,
                            dtype=jnp.float32)
    return e * jnp.sum(onehot.mean(axis=0) * flat_probs.mean(axis=0))


def gather_slot_table(assigned, n: int, capacity: int, e_local: int,
                      e_lo=0):
    """The O(N + E·C) dispatch's slot table for the ``e_local`` experts
    starting at (possibly traced, per-device) index ``e_lo``: kept token n
    occupies slot (idx - e_lo)·C + pos; everything else (drops, other
    devices' experts) writes out of bounds (mode="drop"). Empty slots keep
    the sentinel ``n`` so a gather from an (n+1)-row padded table reads
    the zero row. Shared by the unsharded gather dispatch, the a2a
    shard_map body, and the pipelined MoE block (pipeline.py _moe_mlp)."""
    nslots = e_local * capacity
    sel = jnp.full((nslots,), n, jnp.int32)
    for idx_k, _gate, pos_k, keep_k in assigned:
        idx_l = idx_k - e_lo
        ok = jnp.logical_and(keep_k, jnp.logical_and(idx_l >= 0,
                                                     idx_l < e_local))
        slot = jnp.where(ok, idx_l * capacity + pos_k, nslots)
        sel = sel.at[slot].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    return sel


def combine_from_slots(assigned, eout: jax.Array, n: int, capacity: int,
                       dtype, e_local: int, e_lo=0) -> jax.Array:
    """Inverse of gather_slot_table: per-token gate-weighted gather of the
    expert outputs ``eout`` ((e_local·C), D) back to (n, D). Gates are
    already zeroed for dropped assignments; out-of-range experts (other
    devices') are masked so a psum over the expert axis completes the
    combine."""
    nslots = eout.shape[0]
    out = jnp.zeros((n, eout.shape[1]), dtype)
    for idx_k, gate_k, pos_k, _keep in assigned:
        idx_l = idx_k - e_lo
        ok = jnp.logical_and(idx_l >= 0, idx_l < e_local)
        slot = jnp.clip(idx_l * capacity + pos_k, 0, nslots - 1)
        out = out + (gate_k * ok).astype(dtype)[:, None] \
            * jnp.take(eout, slot, axis=0)
    return out


def expert_ffn(ein: jax.Array, w1, b1, w2, b2, dtype,
               tp_axis=None) -> jax.Array:
    """(E, C, D) expert inputs → (E, C, D) outputs (E may be a local block
    of the stacked expert params).

    ``tp_axis``: Megatron tensor parallelism INSIDE each expert (round 5,
    MoE×tensor): the caller hands hidden-dim shards of w1/b1 (columns) and
    w2 (rows); the down-projection then yields partial sums that one
    ``lax.psum`` completes — same collective count as the dense Megatron
    MLP. b2 is replicated and added AFTER the psum (inside it would be
    multiplied by the axis size). tp_axis=None is the exact same math."""
    h = jnp.einsum("ecd,edf->ecf", ein, w1.astype(dtype)) \
        + b1[:, None, :].astype(dtype)
    h = nn.gelu(h)
    out = jnp.einsum("ecf,efd->ecd", h, w2.astype(dtype))
    if tp_axis is not None:
        out = jax.lax.psum(out, tp_axis)
    return out + b2[:, None, :].astype(dtype)


# (mode, num_experts) pairs already announced by the 'auto' resolution log
# below — once per resolution, not per layer per retrace
_AUTO_RESOLVED_LOGGED: set = set()


class SwitchMlp(nn.Module):
    """Drop-in replacement for the EncoderBlock MLP: LN'd input in,
    residual-branch output out. Shapes: (B, T, D) → (B, T, D)."""

    num_experts: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    dtype: Any = jnp.bfloat16
    mesh: Any = None
    top_k: int = 1
    dispatch: str = "auto"  # auto | einsum | gather | a2a (module docstring)
    # >1 splits tokens into this many capacity groups on the GATHER path —
    # the pure-jit reference of the a2a mode's group-local semantics
    # (parity-tested against it); 1 = global assignment (default)
    capacity_groups: int = 1

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, t, d = x.shape
        e = self.num_experts
        f = self.mlp_ratio * d
        n_tokens = b * t
        if self.top_k not in (1, 2) or self.top_k > e:
            raise ValueError(
                f"moe top_k must be 1 or 2 and <= num_experts={e}, "
                f"got {self.top_k}")
        capacity = max(1, math.ceil(
            self.top_k * (n_tokens / e) * self.capacity_factor))

        vs = jax.nn.initializers.variance_scaling
        w1 = self.param("w1", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, d, f), jnp.float32)
        # "bias" in the name keeps these out of weight decay / LARS trust
        # scaling (the optimizer masks exclude *bias* leaves by path, since
        # expert-stacked biases are 2-D and defeat the ndim heuristic)
        b1 = self.param("bias1", nn.initializers.zeros, (e, f), jnp.float32)
        w2 = self.param("w2", vs(1.0, "fan_in", "truncated_normal",
                                 in_axis=1, out_axis=2, batch_axis=0),
                        (e, f, d), jnp.float32)
        b2 = self.param("bias2", nn.initializers.zeros, (e, d), jnp.float32)

        # --- router (replicated, fp32 for a stable softmax) ---------------
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32))                       # (B, T, E)
        probs = jax.nn.softmax(logits, axis=-1)
        flat_probs = probs.reshape(n_tokens, e)

        # Switch aux loss: E * Σ_e (fraction of tokens routed to e) · (mean
        # router prob of e) — pushes the router toward uniform utilization
        # (first-choice fractions in both routing modes, the Switch form)
        self.sow("losses", "moe_aux", switch_aux_loss(flat_probs))

        mode = self.dispatch
        sharded_e = (self.mesh is not None
                     and self.mesh.shape.get("expert", 1) > 1)
        if mode == "auto":
            if not sharded_e:
                mode = "gather"
            else:
                shards = self._a2a_shards()
                mode = "a2a" if n_tokens % shards == 0 else "einsum"
                # the round-4 a2a path changed what 'auto' resolves to on
                # an expert-sharded mesh, and with it the capacity
                # semantics (group-local vs global cumsum) — say so at
                # trace time so users replaying pre-round-4 runs know to
                # pin dispatch='einsum' (PARITY.md §2.10 records the
                # change). Unsharded meshes keep the unchanged gather
                # semantics — nothing to announce. Once per resolution
                # (not per layer per retrace): a depth-L model would
                # otherwise drown the one-time numerics note in L
                # identical lines every trace.
                e_axis = self.mesh.shape.get("expert", 1)
                log_key = (mode, self.num_experts, e_axis)
                if log_key not in _AUTO_RESOLVED_LOGGED:
                    _AUTO_RESOLVED_LOGGED.add(log_key)
                    import logging
                    logging.getLogger(__name__).info(
                        "SwitchMlp dispatch='auto' resolved to %r (mesh "
                        "expert axis %d); pin model.vit_moe_dispatch to fix "
                        "routing numerics across versions", mode, e_axis)
        if mode not in ("einsum", "gather", "a2a"):
            raise ValueError(f"unknown moe dispatch mode {mode!r}")

        flat_x = x.reshape(n_tokens, d)
        params = (w1, b1, w2, b2)

        if mode == "a2a":
            if not sharded_e:
                raise ValueError(
                    "dispatch='a2a' requires mesh.expert > 1 (tokens are "
                    "exchanged with lax.all_to_all along the expert axis)")
            return self._a2a_dispatch(flat_x, flat_probs, params) \
                .reshape(b, t, d)

        if mode == "gather":
            g = self.capacity_groups
            if n_tokens % g:
                raise ValueError(
                    f"{n_tokens} tokens not divisible into "
                    f"capacity_groups={g}")
            n_g = n_tokens // g
            cap_g = max(1, math.ceil(
                self.top_k * (n_g / e) * self.capacity_factor))
            fn = partial(self._gather_dispatch, capacity=cap_g,
                         params=params)
            if g == 1:
                out = fn(flat_x, flat_probs)
            else:
                out = jax.vmap(fn)(
                    flat_x.reshape(g, n_g, d),
                    flat_probs.reshape(g, n_g, e)).reshape(n_tokens, d)
            return out.reshape(b, t, d)

        # one-hot einsum dispatch (GSPMD shards the E dim over `expert`);
        # global-group capacity assignment
        assigned = _route_assign(flat_probs, e, capacity, self.top_k)
        dispatch = jnp.zeros((n_tokens, e, capacity), jnp.float32)
        combine = jnp.zeros((n_tokens, e, capacity), jnp.float32)
        for idx_k, gate_k, pos_k, keep_k in assigned:
            oh = jax.nn.one_hot(idx_k, e, dtype=jnp.float32)
            d_k = (oh[:, :, None]
                   * jax.nn.one_hot(pos_k, capacity,
                                    dtype=jnp.float32)[:, None, :]
                   * keep_k[:, None, None].astype(jnp.float32))
            dispatch = dispatch + d_k
            combine = combine + d_k * gate_k[:, None, None]

        ein = jnp.einsum("nec,nd->ecd", dispatch.astype(self.dtype),
                         flat_x.astype(self.dtype))
        ein = self._constrain_e(ein)
        eout = self._constrain_e(self._expert_mlp(ein, params))
        out = jnp.einsum("nec,ecd->nd", combine.astype(self.dtype), eout)
        return out.reshape(b, t, d)

    def _expert_mlp(self, ein, params, tp_axis=None):
        return expert_ffn(ein, *params, self.dtype, tp_axis=tp_axis)

    def _gather_dispatch(self, flat_x, flat_probs, capacity, params):
        """O(N + E·C) dispatch for ONE capacity group: scatter the kept
        token ids into an (E·C,) slot table, gather expert inputs by slot,
        gather combines back per token. Dropped assignments write out of
        bounds (mode="drop"); empty slots keep the sentinel N, which
        gathers the appended zero row. No (N, E, C) tensors anywhere."""
        n, d = flat_x.shape
        e = self.num_experts
        assigned = _route_assign(flat_probs, e, capacity, self.top_k)
        sel = gather_slot_table(assigned, n, capacity, e)
        padded = jnp.concatenate(
            [flat_x.astype(self.dtype),
             jnp.zeros((1, d), self.dtype)], axis=0)
        ein = jnp.take(padded, sel, axis=0).reshape(e, capacity, d)
        eout = self._expert_mlp(ein, params).reshape(e * capacity, d)
        return combine_from_slots(assigned, eout, n, capacity,
                                  self.dtype, e)

    def _a2a_shards(self) -> int:
        return math.prod(self.mesh.shape.get(a, 1)
                         for a in ("data", "fsdp", "expert"))

    def _a2a_dispatch(self, flat_x, flat_probs, params):
        """Hand-scheduled expert parallelism (module docstring): shard_map
        over (data, fsdp, expert), group-local O(N+EC) gather dispatch,
        ONE all_to_all each way along ``expert``. Expert FLOPs are spread
        over ALL mesh devices (the einsum path replicates them across the
        batch axes), and the only exchanged buffers are the (E, C_sub, D)
        expert inputs/outputs."""
        from ..parallel.mesh import shard_map_compat
        mesh, e = self.mesh, self.num_experts
        ep = mesh.shape.get("expert", 1)
        n_tokens, d = flat_x.shape
        shards = self._a2a_shards()
        if n_tokens % shards:
            raise ValueError(
                f"dispatch='a2a' needs tokens ({n_tokens}) divisible by "
                f"data x fsdp x expert shards ({shards})")
        n_sub = n_tokens // shards
        cap = max(1, math.ceil(
            self.top_k * (n_sub / e) * self.capacity_factor))
        e_loc = e // ep
        dtype, top_k = self.dtype, self.top_k
        expert_mlp = self._expert_mlp
        # MoE×tensor (round 5): each expert's FFN is Megatron-sharded over
        # `tensor` (w1/b1 columns, w2 rows — parallel/sharding.py); the
        # tokens stay REPLICATED across `tensor` (unmentioned in `tok`),
        # so every tensor peer runs identical routing and exchanges, and
        # one psum inside expert_ffn completes the down-projection.
        tp = mesh.shape.get("tensor", 1)
        f = params[0].shape[-1]
        tp_axis = "tensor" if (tp > 1 and f % tp == 0) else None

        def body(xs, ps, w1l, b1l, w2l, b2l):
            # xs (n_sub, d) this device's token sub-shard; ps (n_sub, e);
            # w*l the local expert block (e_loc, ...)
            assigned = _route_assign(ps, e, cap, top_k)
            sel = gather_slot_table(assigned, n_sub, cap, e)
            padded = jnp.concatenate(
                [xs.astype(dtype), jnp.zeros((1, d), dtype)], axis=0)
            # (ep, e_loc, cap, d): row j = my tokens for expert chunk j
            ein = jnp.take(padded, sel, axis=0).reshape(ep, e_loc, cap, d)
            # after a2a row p = peer p's tokens for MY chunk
            ein = jax.lax.all_to_all(ein, "expert", 0, 0)
            ein = ein.transpose(1, 0, 2, 3).reshape(e_loc, ep * cap, d)
            eo = expert_mlp(ein, (w1l, b1l, w2l, b2l), tp_axis)
            eo = eo.reshape(e_loc, ep, cap, d).transpose(1, 0, 2, 3)
            # send peer p's token outputs home; receive mine from each chunk
            eo = jax.lax.all_to_all(eo, "expert", 0, 0)
            eout = eo.reshape(e * cap, d)
            return combine_from_slots(assigned, eout, n_sub, cap, dtype, e)

        tok = P(("data", "fsdp", "expert"), None)
        tps = "tensor" if tp_axis else None
        sharded = shard_map_compat(
            body, mesh,
            in_specs=(tok, tok, P("expert", None, tps), P("expert", tps),
                      P("expert", tps, None), P("expert", None)),
            out_specs=tok)
        w1, b1, w2, b2 = params
        return sharded(flat_x, flat_probs, w1, b1, w2, b2)

    def _constrain_e(self, arr):
        """Pin the expert dim to the `expert` axis so expert compute stays
        where the weights live."""
        mesh = self.mesh
        if mesh is None or mesh.shape.get("expert", 1) <= 1:
            return arr
        from jax.sharding import NamedSharding
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, P("expert", None, None)))
