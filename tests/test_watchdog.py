"""Watchdog + heartbeat unit suite (resilience/watchdog.py,
resilience/heartbeat.py): every detection/escalation path driven with a
fake transport, fake clocks and a fake exit — no subprocesses, no sleeps.
The real 2-process kill-and-detect coverage lives in tests/test_resilience.py
(@heavy); scripts/chaos_smoke.sh --fast runs this file's set."""
import json
import os
import threading
import time

import pytest

from distributed_resnet_tensorflow_tpu.resilience.heartbeat import (
    Beat, BeatTransport, FileBeatTransport, HeartbeatPublisher,
    PHASE_DONE, PHASE_FAILED)
from distributed_resnet_tensorflow_tpu.resilience.watchdog import (
    FAILURE_EXIT_CODE, Watchdog, watchdog_enabled)
from distributed_resnet_tensorflow_tpu.resilience.preemption import (
    PreemptionListener, RESUMABLE_EXIT_CODE)
from distributed_resnet_tensorflow_tpu.utils.config import WatchdogConfig


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


class MemoryTransport(BeatTransport):
    def __init__(self):
        self.beats = {}
        self.published = []

    def publish(self, beat: Beat) -> None:
        self.beats[beat.process_id] = beat
        self.published.append(beat)

    def peers(self):
        return dict(self.beats)


class FakeWriter:
    def __init__(self):
        self.events = []

    def write_event(self, event, payload):
        self.events.append({"event": event, **payload})

    def flush(self):
        pass

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]


class ExitCalled(Exception):
    def __init__(self, code):
        super().__init__(f"exit({code})")
        self.code = code


def _beat(pid, step=0, phase="train", wall_time=1000.0, progress=None):
    return Beat(process_id=pid, pid=100 + pid, host=f"h{pid}", seq=1,
                step=step, progress=step if progress is None else progress,
                phase=phase, wall_time=wall_time)


def make_watchdog(num_processes=2, process_id=0, writer=None,
                  request_stop=None, **cfg_kw):
    cfg = WatchdogConfig(interval_secs=1.0, peer_timeout_secs=10.0,
                         step_timeout_scale=10.0, min_step_timeout_secs=30.0,
                         grace_secs=5.0, straggler_window_secs=60.0,
                         straggler_ratio=1.5)
    for k, v in cfg_kw.items():
        setattr(cfg, k, v)
    clock = FakeClock()
    transport = MemoryTransport()
    publisher = HeartbeatPublisher(transport, process_id, clock=clock,
                                   wall_clock=clock)
    exits = []

    def exit_fn(code):
        exits.append(code)
        raise ExitCalled(code)

    wd = Watchdog(transport, publisher, process_id, num_processes, cfg,
                  writer=writer, request_stop=request_stop, clock=clock,
                  wall_clock=clock, exit_fn=exit_fn)
    return wd, transport, publisher, clock, exits


# ---------------------------------------------------------------------------
# enable resolution
# ---------------------------------------------------------------------------

def test_watchdog_enabled_tristate():
    cfg = WatchdogConfig()
    assert not watchdog_enabled(cfg, 1)   # auto: nothing to watch solo
    assert watchdog_enabled(cfg, 2)
    cfg.enabled = "on"
    assert watchdog_enabled(cfg, 1)
    cfg.enabled = "off"
    assert not watchdog_enabled(cfg, 8)
    cfg.enabled = "sometimes"
    with pytest.raises(ValueError):
        watchdog_enabled(cfg, 2)


# ---------------------------------------------------------------------------
# peer loss
# ---------------------------------------------------------------------------

def test_peer_loss_detected_and_hard_exits_75_after_grace():
    stops = []
    wd, tr, pub, clock, exits = make_watchdog(
        request_stop=lambda reason: stops.append(reason))
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t))
    wd._tick(clock.t)                     # fresh: no verdict
    assert wd.fired() is None
    clock.advance(11.0)                   # beat now 11s old > 10s timeout
    pub.tick()                            # we ourselves stay live
    wd._tick(clock.t)
    assert wd.fired() == "peer_lost"
    assert stops == ["peer_lost"]         # graceful stop requested first
    clock.advance(1.0)
    wd._tick(clock.t)                     # inside grace: no exit yet
    assert not exits
    clock.advance(5.0)
    pub.tick()                            # main thread alive is irrelevant:
    with pytest.raises(ExitCalled):       # the peer is still gone
        wd._tick(clock.t)
    assert exits == [RESUMABLE_EXIT_CODE]


def test_peer_beats_resuming_cancels_teardown():
    wd, tr, pub, clock, exits = make_watchdog()
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t))
    clock.advance(11.0)
    pub.tick()
    wd._tick(clock.t)
    assert wd.fired() == "peer_lost"
    # the peer was only GC-paused: beats resume within the grace window
    tr.publish(_beat(1, step=2, wall_time=clock.t))
    clock.advance(6.0)
    tr.publish(_beat(1, step=3, wall_time=clock.t))
    pub.tick()
    wd._tick(clock.t)
    assert not exits and wd.fired() is None


def test_departed_peers_are_not_flagged():
    wd, tr, pub, clock, exits = make_watchdog(num_processes=3)
    pub.update(step=5, phase="train")
    tr.publish(_beat(1, step=5, phase=PHASE_DONE, wall_time=clock.t))
    tr.publish(_beat(2, step=5, phase="preempted", wall_time=clock.t))
    clock.advance(100.0)
    pub.tick()
    wd._tick(clock.t)
    assert wd.fired() is None and not exits


def test_never_seen_peer_is_not_flagged():
    # bootstrap races belong to the distributed-init retry, not the watchdog
    wd, tr, pub, clock, exits = make_watchdog(num_processes=2)
    pub.update(step=1, phase="train")
    clock.advance(100.0)
    pub.tick()
    wd._tick(clock.t)
    assert wd.fired() is None


def test_peer_failed_beat_escalates_with_failure_code():
    wd, tr, pub, clock, exits = make_watchdog(grace_secs=0.0)
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, phase=PHASE_FAILED, wall_time=clock.t))
    wd._tick(clock.t)
    assert wd.fired() == "peer_failed"
    clock.advance(0.1)
    with pytest.raises(ExitCalled):
        wd._tick(clock.t)
    assert exits == [FAILURE_EXIT_CODE]   # real failure must NOT requeue


def test_failed_beat_during_grace_upgrades_peer_lost_to_failure_code():
    # peer 1 goes stale -> peer_lost (75) fires; its final failed beat
    # lands DURING the grace window (slow FS) -> the exit must carry the
    # failure code, not requeue-mask the real error as preemption
    wd, tr, pub, clock, exits = make_watchdog()
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t))
    clock.advance(11.0)
    pub.tick()
    wd._tick(clock.t)
    assert wd.fired() == "peer_lost"
    tr.publish(_beat(1, step=1, phase=PHASE_FAILED, wall_time=clock.t))
    clock.advance(6.0)
    pub.tick()
    with pytest.raises(ExitCalled):
        wd._tick(clock.t)
    assert exits == [FAILURE_EXIT_CODE]


def test_peer_failed_outranks_another_peers_staleness():
    # peer 1 merely stale, peer 2 published a fatal beat: the verdict must
    # be peer_failed regardless of scan order
    wd, tr, pub, clock, exits = make_watchdog(num_processes=3,
                                              grace_secs=0.0)
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t - 11.0))  # stale
    tr.publish(_beat(2, step=1, phase=PHASE_FAILED, wall_time=clock.t))
    wd._tick(clock.t)
    assert wd.fired() == "peer_failed"
    clock.advance(0.1)
    with pytest.raises(ExitCalled):
        wd._tick(clock.t)
    assert exits == [FAILURE_EXIT_CODE]


# ---------------------------------------------------------------------------
# hang detection + rolling deadline
# ---------------------------------------------------------------------------

def test_hang_detected_when_progress_stalls_past_min_deadline():
    wd, tr, pub, clock, exits = make_watchdog(num_processes=1,
                                              min_step_timeout_secs=30.0)
    pub.update(step=1, phase="train")
    clock.advance(29.0)
    wd._tick(clock.t)
    assert wd.fired() is None             # under the deadline
    clock.advance(2.0)
    wd._tick(clock.t)
    assert wd.fired() == "hang"


def test_hang_deadline_scales_with_rolling_step_time():
    wd, tr, pub, clock, exits = make_watchdog(
        num_processes=1, min_step_timeout_secs=5.0, step_timeout_scale=10.0)
    # steps at ~2s each: the EWMA-derived deadline (10 x 2s) must dominate
    # the 5s floor. First delta (compile-laden) is discarded by design.
    for step in range(1, 6):
        pub.update(step=step, phase="train")
        clock.advance(2.0)
    assert pub.snapshot()["ewma_step_secs"] == pytest.approx(2.0)
    clock.advance(8.0)                    # 10s stalled: > floor, < 10x2s
    wd._tick(clock.t)
    assert wd.fired() is None
    clock.advance(11.0)                   # 21s > 20s rolling deadline
    wd._tick(clock.t)
    assert wd.fired() == "hang"


def test_hang_deadline_scales_with_fused_loop_stride():
    """With train.steps_per_loop=K the publisher only sees one update per
    K steps: the deadline must be per UPDATE (est x stride x scale), or a
    healthy 64-step scan outlives a 10x-one-step deadline mid-loop."""
    wd, tr, pub, clock, exits = make_watchdog(
        num_processes=1, min_step_timeout_secs=5.0, step_timeout_scale=10.0)
    for i in range(1, 6):                 # updates every 64 steps, 2s/step
        pub.update(step=64 * i, phase="train")
        clock.advance(128.0)
    snap = pub.snapshot()
    assert snap["ewma_step_secs"] == pytest.approx(2.0)
    assert snap["step_stride"] == 64
    clock.advance(200.0)                  # mid-scan: way past 10x2s=20s
    wd._tick(clock.t)
    assert wd.fired() is None             # healthy loop, not a hang
    clock.advance(1200.0)                 # 1400s > 10 x 2s x 64 = 1280s
    wd._tick(clock.t)
    assert wd.fired() == "hang"


def test_peer_loss_exit_deferred_while_final_save_in_flight():
    """Grace expiry must not os._exit mid-save: the coordinated stop's
    whole point is committing that final checkpoint. Bounded — a save
    wedged past the deferral cap still dies."""
    wd, tr, pub, clock, exits = make_watchdog(
        grace_secs=5.0, min_step_timeout_secs=30.0)
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t))
    wd._tick(clock.t)
    clock.advance(11.0)
    pub.tick()
    wd._tick(clock.t)
    assert wd.fired() == "peer_lost"
    pub.set_phase("save")                 # stop honored: final save running
    clock.advance(6.0)                    # grace expired, save in flight
    wd._tick(clock.t)
    assert not exits                      # deferred, not torn mid-save
    clock.advance(26.0)                   # 32s > cap max(5, 30): wedged
    with pytest.raises(ExitCalled):
        wd._tick(clock.t)
    assert exits == [RESUMABLE_EXIT_CODE]


def test_no_hang_detection_outside_monitored_phases():
    wd, tr, pub, clock, exits = make_watchdog(num_processes=1,
                                              min_step_timeout_secs=5.0)
    pub.set_phase("init")                 # compile/restore take arbitrarily long
    clock.advance(1000.0)
    wd._tick(clock.t)
    assert wd.fired() is None
    pub.set_phase("save")
    clock.advance(1000.0)
    wd._tick(clock.t)
    assert wd.fired() is None


def test_hang_clearing_in_grace_cancels_exit():
    wd, tr, pub, clock, exits = make_watchdog(num_processes=1,
                                              min_step_timeout_secs=5.0,
                                              grace_secs=10.0)
    pub.update(step=1, phase="train")
    clock.advance(6.0)
    wd._tick(clock.t)
    assert wd.fired() == "hang"
    pub.update(step=2)                    # the step finally landed
    clock.advance(11.0)
    wd._tick(clock.t)
    assert not exits and wd.fired() is None


def test_disarm_suppresses_exit():
    wd, tr, pub, clock, exits = make_watchdog(num_processes=1,
                                              min_step_timeout_secs=5.0,
                                              grace_secs=1.0)
    pub.update(step=1, phase="train")
    clock.advance(6.0)
    wd._tick(clock.t)
    assert wd.fired() == "hang"
    wd.disarm()                           # orderly shutdown owns the exit now
    clock.advance(100.0)
    wd._tick(clock.t)
    assert not exits


# ---------------------------------------------------------------------------
# exception-path classification
# ---------------------------------------------------------------------------

def test_failure_verdict_confirms_stale_peer():
    wd, tr, pub, clock, exits = make_watchdog()
    tr.publish(_beat(1, step=3, wall_time=clock.t))
    wd._tick(clock.t)                     # peer registered while fresh
    clock.advance(11.0)
    kind, code, detail = wd.failure_verdict(wait_secs=0.0)
    assert kind == "peer_lost" and code == RESUMABLE_EXIT_CODE
    assert "process 1" in detail


def test_failure_verdict_none_when_peers_healthy():
    wd, tr, pub, clock, exits = make_watchdog()
    tr.publish(_beat(1, step=3, wall_time=clock.t))
    assert wd.failure_verdict(wait_secs=0.0) is None


# ---------------------------------------------------------------------------
# straggler accounting + heartbeat export
# ---------------------------------------------------------------------------

def test_straggler_rows_flag_slow_host():
    writer = FakeWriter()
    wd, tr, pub, clock, exits = make_watchdog(
        num_processes=2, writer=writer, straggler_window_secs=10.0,
        straggler_ratio=1.5, peer_timeout_secs=1e9)
    # 10 ticks: process 0 runs 10 steps/s, process 1 only 2 steps/s
    for i in range(12):
        tr.publish(_beat(0, step=10 * i, wall_time=clock.t))
        tr.publish(_beat(1, step=2 * i, wall_time=clock.t))
        wd._tick(clock.t)
        clock.advance(1.0)
    rows = writer.of("straggler")
    assert rows, "straggler accounting rows must appear"
    last = rows[-1]
    assert last["flagged"] == [1]
    assert last["rates"]["1"] < last["rates"]["0"]
    assert last["lag_steps"]["1"] > 0
    hb = writer.of("heartbeat")
    assert hb and set(hb[-1]["hosts"]) == {"0", "1"}


def test_straggler_median_is_true_median_on_even_host_count():
    """2-host world, rates 9.0 vs 5.9: the upper-middle element would be
    the MAX (9.0/5.9 = 1.53 >= 1.5, spurious flag); the true median 7.45
    gives 1.26 and must flag nothing."""
    writer = FakeWriter()
    wd, tr, pub, clock, exits = make_watchdog(
        num_processes=2, writer=writer, straggler_window_secs=10.0,
        straggler_ratio=1.5, peer_timeout_secs=1e9)
    for i in range(12):
        tr.publish(_beat(0, step=int(90 * i), wall_time=clock.t))
        tr.publish(_beat(1, step=int(59 * i), wall_time=clock.t))
        wd._tick(clock.t)
        clock.advance(1.0)
    rows = writer.of("straggler")
    assert rows and rows[-1]["flagged"] == []
    assert abs(rows[-1]["median"] - (90 + 59) / 2.0) < 1.0


def test_straggler_rows_on_balanced_hosts_flag_nothing():
    writer = FakeWriter()
    wd, tr, pub, clock, exits = make_watchdog(
        num_processes=2, writer=writer, straggler_window_secs=10.0,
        peer_timeout_secs=1e9)
    for i in range(12):
        for pid in (0, 1):
            tr.publish(_beat(pid, step=5 * i, wall_time=clock.t))
        wd._tick(clock.t)
        clock.advance(1.0)
    rows = writer.of("straggler")
    assert rows and rows[-1]["flagged"] == []


def test_escalation_writes_event_rows():
    writer = FakeWriter()
    stops = []
    wd, tr, pub, clock, exits = make_watchdog(
        writer=writer, request_stop=stops.append, grace_secs=1.0)
    pub.update(step=1, phase="train")
    tr.publish(_beat(1, step=1, wall_time=clock.t))
    clock.advance(11.0)
    pub.tick()
    wd._tick(clock.t)
    assert [e["event"] for e in writer.events
            if e["event"] == "peer_lost"] == ["peer_lost"]
    clock.advance(2.0)
    pub.tick()
    with pytest.raises(ExitCalled):
        wd._tick(clock.t)
    kinds = [e["event"] for e in writer.events]
    assert "watchdog_exit" in kinds


# ---------------------------------------------------------------------------
# heartbeat publisher + file transport
# ---------------------------------------------------------------------------

def test_publisher_thread_beats_while_main_thread_blocked(tmp_path):
    tr = FileBeatTransport(str(tmp_path), 0)
    pub = HeartbeatPublisher(tr, 0, interval_secs=0.05)
    pub.start()
    try:
        pub.update(step=3, phase="train")
        deadline = time.monotonic() + 5.0
        seq = None
        while time.monotonic() < deadline:
            beats = tr.peers()
            if 0 in beats and beats[0].step == 3 and beats[0].seq >= 3:
                seq = beats[0].seq
                break
            time.sleep(0.02)
        assert seq is not None, "publisher thread never beat"
    finally:
        pub.close()
    assert tr.peers()[0].phase == PHASE_DONE  # final beat marks departure


def test_file_transport_roundtrip_and_junk_tolerance(tmp_path):
    # epoch-0 clocks: the fixture beats carry wall_time=1000.0, which the
    # previous-run filter would drop against the real time.time() epoch
    t0 = FileBeatTransport(str(tmp_path), 0, wall_clock=lambda: 0.0)
    t1 = FileBeatTransport(str(tmp_path), 1, wall_clock=lambda: 0.0)
    t0.publish(_beat(0, step=7))
    t1.publish(_beat(1, step=9))
    # torn/garbage files must be skipped, not fatal
    with open(os.path.join(str(tmp_path), "proc2.json"), "w") as f:
        f.write('{"process_id": 2, "ste')
    with open(os.path.join(str(tmp_path), "ignore.txt"), "w") as f:
        f.write("not a beat")
    peers = t0.peers()
    assert set(peers) == {0, 1}
    assert peers[1].step == 9


def test_file_transport_clears_own_stale_file(tmp_path):
    # a relaunch must not inherit last run's (dead-looking) beat
    FileBeatTransport(str(tmp_path), 0).publish(
        _beat(0, step=100, wall_time=1.0))
    t = FileBeatTransport(str(tmp_path), 0)
    assert 0 not in t.peers()


def test_file_transport_final_beat_outranks_straggling_live_beat(tmp_path):
    """A publisher thread stuck in a shared-FS stall can land a stale
    phase="train" beat AFTER close() published the final "done" — the
    sidecar final file must still win, or survivors watch the stale beat
    age into a spurious peer_lost 75 for a peer that finished cleanly."""
    t = FileBeatTransport(str(tmp_path), 0, wall_clock=lambda: 0.0)
    t.publish(_beat(0, step=9, phase="train", wall_time=10.0))
    t.publish(_beat(0, step=10, phase=PHASE_DONE, wall_time=11.0))
    # the stuck thread's write completes last, replacing the regular file
    t.publish(_beat(0, step=9, phase="train", wall_time=10.5))
    assert t.peers()[0].phase == PHASE_DONE


def test_file_transport_ignores_previous_run_peer_beats(tmp_path):
    # after a requeue the shared dir still holds every OTHER process's
    # previous-run file; a fast-starting peer must not read one (arbitrarily
    # old, possibly phase="failed") and fire a spurious teardown before the
    # slow peer's first beat of THIS run lands
    FileBeatTransport(str(tmp_path), 1, wall_clock=lambda: 50.0).publish(
        _beat(1, step=100, wall_time=60.0, phase="failed"))
    t0 = FileBeatTransport(str(tmp_path), 0, wall_clock=lambda: 100.0)
    assert 1 not in t0.peers()    # published before our epoch: filtered
    FileBeatTransport(str(tmp_path), 1, wall_clock=lambda: 110.0).publish(
        _beat(1, step=3, wall_time=120.0))
    assert t0.peers()[1].step == 3  # the new run's beat becomes visible


def test_ewma_skips_post_interlude_delta():
    """The first step delta after an eval/save pause spans the whole pause;
    folding it in (alpha 0.3) would inflate the hang deadline by hours —
    it must be discarded like the compile-laden first delta."""
    clock = FakeClock()
    tr = MemoryTransport()
    pub = HeartbeatPublisher(tr, 0, clock=clock, wall_clock=clock)
    for step in (1, 2, 3, 4):
        pub.update(step=step, phase="train")
        clock.advance(1.0)
    ewma = pub.snapshot()["ewma_step_secs"]
    assert ewma == pytest.approx(1.0)
    pub.tick(phase="eval")            # 30-minute eval round
    clock.advance(1800.0)
    pub.update(step=5, phase="train")  # delta spans the pause: discarded
    assert pub.snapshot()["ewma_step_secs"] == pytest.approx(ewma)
    clock.advance(1.0)
    pub.update(step=6, phase="train")  # steady state resumes folding
    assert pub.snapshot()["ewma_step_secs"] == pytest.approx(1.0)


def test_exit_suppressed_when_disarmed_mid_verdict():
    """disarm() landing while the daemon is inside the slow verdict
    re-check must suppress the hard exit — a completed run must never be
    75'd by its own watchdog."""
    wd, tr, pub, clock, exits = make_watchdog()
    wd.disarm()
    wd.exit_now("peer_lost", RESUMABLE_EXIT_CODE, "stale test peer")
    assert exits == []


def test_publisher_progress_counts_eval_ticks():
    clock = FakeClock()
    tr = MemoryTransport()
    pub = HeartbeatPublisher(tr, 0, clock=clock, wall_clock=clock)
    pub.update(step=1, phase="train")
    p0 = pub.snapshot()["progress"]
    pub.tick(phase="eval")
    pub.tick()
    snap = pub.snapshot()
    assert snap["progress"] == p0 + 2
    assert snap["phase"] == "eval"
    assert snap["step"] == 1              # eval must not move the step


def test_listener_request_stop_feeds_stop_poll():
    listener = PreemptionListener(signals=())
    assert not listener.should_stop()
    listener.request_stop("peer_lost")
    assert listener.should_stop()
    assert listener.reason() == "peer_lost"
