#!/bin/bash
# Shardcheck gate — the seconds-fast correctness check that runs BEFORE a
# cluster allocation is spent (docs/static_analysis.md):
#
#   * project-invariant lint (analysis/rules/): stray device_put, cached
#     meshes, bare asserts, undeclared exit codes, metrics-event/config
#     drift against the declared registries;
#   * static elaboration (analysis/elaborate.py): every preset × mesh
#     layout traced abstractly on a virtual CPU mesh — PartitionSpec,
#     shape and config bugs surface here with the offending param path,
#     not as a step-1 _SpecError after a 20-minute queue wait.
#
#   scripts/analysis_gate.sh                 # full gate (lint + elaborate
#                                            #   + zero1 sweep + hangcheck
#                                            #   + plan-drift + protocol)
#   scripts/analysis_gate.sh --lint-only     # sub-second syntax/invariant pass
#   scripts/analysis_gate.sh --no-hangcheck  # skip the hangcheck phases
#                                            #   (mirrors --no-zero1-sweep,
#                                            #   --no-plan-drift,
#                                            #   --no-protocol)
#
# Wired as a pre-submit step in scripts/submit_tpu_slurm.sh and into the
# pre-merge chaos gate (scripts/chaos_smoke.sh --fast). Exit 0 = clean,
# 1 = findings (per the resilience.EXIT_CONTRACT failure code).
#
# Budget contract (docs/static_analysis.md): the FULL gate finishes in
# <300 s — per-phase wall times are printed by the check CLI (lint /
# elaborate / elab-zero1 / hangcheck-schedule / plan-drift / protocol
# lines — the plan-drift phase (ISSUE 17, docs/planner.md) re-costs the
# what-if planner over the committed schedules and refreshes
# analysis/plan_catalog.json; measured ~3-6 s; the protocol phase
# (ISSUE 20) exhaustively model-checks the four declared control-plane
# protocols and refreshes analysis/protocol_models.json; measured
# <0.5 s — both well inside the same
# 300 s envelope), and this script
# fails loudly when the total busts the budget, so creep shows up as a
# red gate in the PR that caused it, not as a slow submit host months
# later. Scoped runs (--lint-only, --preset, --no-*) enforce the same
# ceiling trivially.
#
# Budget history: the original <120 s contract was measured against the
# pre-universal-envelope gate (~105 s) and TRIPPED at HEAD on a loaded
# container (129 s, 0 findings — wall time on this box drifts ~2x under
# concurrent load for identical code). The universal overlap envelope
# (ISSUE 15) legitimately grew coverage — transformer-family overlap /
# compress traces, the vit_moe preset, accumulation schedules, int8
# variant traces — to a measured ~160-200 s full gate. 300 s = measured
# unloaded time + the observed load drift; raise it only with a matching
# measurement, and look at the per-phase echo before blaming the budget.
set -euo pipefail
cd "$(dirname "$0")/.."

GATE_BUDGET_SECS=${GATE_BUDGET_SECS:-300}
start=$(date +%s)

# all presets is `check`'s default — not hardcoded here, so pass-through
# args like `--preset smoke` or `--lint-only` scope the gate cleanly
rc=0
env JAX_PLATFORMS=cpu python -m distributed_resnet_tensorflow_tpu.main \
  check "$@" || rc=$?

elapsed=$(( $(date +%s) - start ))
echo "analysis_gate: total ${elapsed}s (budget ${GATE_BUDGET_SECS}s)"
if [[ $elapsed -gt $GATE_BUDGET_SECS ]]; then
  echo "analysis_gate: BUDGET EXCEEDED — the gate took ${elapsed}s," \
       "contract is <${GATE_BUDGET_SECS}s (docs/static_analysis.md)." \
       "Find the phase that crept in the per-phase times above." >&2
  exit 1
fi
exit $rc
