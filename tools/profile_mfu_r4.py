"""Round-4 ImageNet RN50 step-time experiments (VERDICT r3 items 1+2).

Measures optimizer-step time / img/s / MFU for a grid of variants on the
real chip, attacking the two levers the round-3 trace localized
(docs/perf_imagenet_r3_ops.json): the scan-carry copy tax (~2.5 ms/step of
tiny async copies double-buffering the TrainState through the
steps_per_loop while loop) and conv efficiency (~75% of the MXU floor).

Variants are selected by name on the CLI so a partial grid can run inside
any time budget:

    python tools/profile_mfu_r4.py baseline unroll bs32 bs64 ...

Writes/merges docs/perf_imagenet_r4.json.
"""
from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
OUT = os.path.join(REPO, "docs", "perf_imagenet_r4.json")


def measure(bs: int, k: int = 8, unroll: int = 1, reps: int = 5,
            loops: int = 5, **cfg_overrides):
    """One grid point: fused k-step dispatch, best-of-reps wall clock."""
    from distributed_resnet_tensorflow_tpu.parallel.sharding import (
        shard_batch, shard_stacked_batch)
    from distributed_resnet_tensorflow_tpu.train import Trainer
    from distributed_resnet_tensorflow_tpu.utils import profiling
    from distributed_resnet_tensorflow_tpu.utils.config import get_preset

    cfg = get_preset("imagenet_resnet50")
    cfg.data.dataset = "imagenet"
    cfg.train.batch_size = bs
    cfg.train.steps_per_loop = k
    cfg.train.scan_unroll = unroll
    cfg.mesh.data = len(jax.devices())
    for dotted, v in cfg_overrides.items():
        cfg.override(dotted, v)
    trainer = Trainer(cfg)
    trainer.init_state()
    multi_fn = trainer.jitted_multi_step(k)
    rng = np.random.RandomState(0)
    batch = shard_stacked_batch({
        "images": rng.randn(k, bs, 224, 224, 3).astype(np.float32),
        "labels": rng.randint(0, 1001, (k, bs)).astype(np.int32),
    }, trainer.mesh)
    state = trainer.state
    t_c = time.perf_counter()
    for _ in range(2):
        state, _m = multi_fn(state, batch)
    jax.block_until_ready(state.params)
    compile_s = time.perf_counter() - t_c
    # the jitted step donates the state arg, so never rewind to an already-
    # consumed state — carry it forward through every rep like training does
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(loops):
            state, _m = multi_fn(state, batch)
        jax.block_until_ready(state.params)
        best = min(best, time.perf_counter() - t0)
    steps_per_sec = loops * k / best

    single = trainer.jitted_train_step()
    one = shard_batch({"images": np.asarray(batch["images"])[0],
                       "labels": np.asarray(batch["labels"])[0]},
                      trainer.mesh)
    step_flops = profiling.flops_per_step(single, state, one)
    util = profiling.mfu(steps_per_sec, step_flops) if step_flops else None
    return {
        "bs": bs, "k": k, "unroll": unroll,
        "ms_per_step": round(1000.0 / steps_per_sec, 2),
        "images_per_sec": round(steps_per_sec * bs, 1),
        "mfu": round(util, 4) if util else None,
        "step_flops": step_flops,
        "compile_plus_warmup_s": round(compile_s, 1),
        **({"overrides": cfg_overrides} if cfg_overrides else {}),
    }


# NOTE on historical labels: rows in docs/perf_imagenet_r4.json were
# measured as the code evolved during round 4 (docs/perf_imagenet_r4.md
# records which code state each row reflects). On CURRENT code the defaults
# already include the kept levers (s2d stem, SAME maxpool), so "baseline"
# measures the shipping configuration; "no_s2d" reproduces the non-s2d
# floor. The "maxpool"/"s2d"* labels in the JSON are historical snapshots.
VARIANTS = {
    "baseline": lambda: measure(128, 8, 1),
    "no_s2d": lambda: measure(128, 8, 1,
                              **{"model.stem_space_to_depth": False}),
    # scan-unroll family — REFUTED (measured a wash; kept for reproduction)
    "unroll": lambda: measure(128, 8, 8),
    "unroll2": lambda: measure(128, 8, 2),
    "unroll4": lambda: measure(128, 8, 4),
    "k4_unroll": lambda: measure(128, 4, 4, loops=10),
    "k2_unroll": lambda: measure(128, 2, 2, loops=20),
    # dispatch-overhead control: k=1 (no scan at all, donation in place)
    "k1": lambda: measure(128, 1, 1, loops=40),
    # the per-chip batch regime rows (unroll stays 1 — measured a wash)
    "bs16": lambda: measure(16, 8, 1, loops=30),
    "bs32": lambda: measure(32, 8, 1, loops=20),
    "bs64": lambda: measure(64, 8, 1, loops=10),
    "bs256": lambda: measure(256, 8, 1, loops=3),
}


def main():
    names = sys.argv[1:] or ["baseline", "unroll"]
    results = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            results = json.load(f)
    for name in names:
        if name not in VARIANTS:
            print(f"unknown variant {name!r}; have {sorted(VARIANTS)}")
            continue
        t0 = time.time()
        try:
            r = VARIANTS[name]()
        except Exception as e:
            r = {"error": f"{type(e).__name__}: {e}"[:300]}
        r["wall_s"] = round(time.time() - t0, 1)
        results[name] = r
        print(json.dumps({name: r}))
        with open(OUT, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
