#!/bin/bash
# Chaos smoke — run the fault-injection suite (resilience/faultinject.py):
# signal delivery mid-run, torn/bit-rotted checkpoints, injected NaN loss,
# plus the watchdog cases (killed peer, frozen peer, straggler —
# tests/test_watchdog.py + the subprocess kill-and-detect tests in
# tests/test_resilience.py). Everything runs on the fake-CPU mesh
# (tests/conftest.py) — no accelerator needed.
#
#   scripts/chaos_smoke.sh            # the tier-1 chaos set (incl. @heavy
#                                     # multi-process subprocess tests,
#                                     # ~minutes of real training children)
#   scripts/chaos_smoke.sh --fast     # seconds-fast pre-merge gate:
#                                     # -m "not slow and not heavy"
#   scripts/chaos_smoke.sh -k nan     # just the NaN-recovery cases
set -euo pipefail
cd "$(dirname "$0")/.."

MARKS="not slow"
if [[ "${1:-}" == "--fast" ]]; then
  MARKS="not slow and not heavy"
  shift
fi

exec env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_resilience.py tests/test_watchdog.py -q \
  -m "$MARKS" -p no:cacheprovider "$@"
