"""telemetry/ suite: flight-recorder trace well-formedness (Chrome-trace
JSON, per-thread nesting, ring bound), fake-clock goodput classification,
metrics.jsonl rotation + read-back, decode-process counter ship-back, the
cluster monitor aggregate, and the watchdog's anomaly-triggered dump."""
import glob
import json
import os
import queue
import threading
import time

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.telemetry.goodput import (
    CATEGORIES, GoodputMeter, goodput)
from distributed_resnet_tensorflow_tpu.telemetry.tracer import (
    SPAN_CATALOG, SPAN_SCHEMA_VERSION, FlightRecorder, recorder)
from distributed_resnet_tensorflow_tpu.utils.metrics import (
    EVENT_SCHEMAS, MetricsWriter, StageStats, read_metrics)


class FakeWriter:
    def __init__(self):
        self.events = []

    def write_event(self, event, payload):
        self.events.append({"event": event, **payload})

    def flush(self):
        pass


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_trace_dump_is_wellformed_chrome_trace(tmp_path):
    rec = FlightRecorder(ring=1024)
    with rec.span("train.step"):
        with rec.span("input.wait"):
            time.sleep(0.002)
        time.sleep(0.002)

    def worker():
        with rec.span("input.stage"):
            time.sleep(0.002)

    t = threading.Thread(target=worker, name="stage-thread")
    t.start()
    t.join()

    path = rec.dump(str(tmp_path / "trace.json"), reason="test")
    doc = json.load(open(path))  # loads = Perfetto/chrome://tracing accepts
    assert isinstance(doc["traceEvents"], list)
    assert doc["otherData"]["span_schema_version"] == SPAN_SCHEMA_VERSION
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"train.step", "input.wait",
                                       "input.stage"}
    for e in xs:
        assert e["ts"] >= 0 and e["dur"] > 0 and "tid" in e and "pid" in e
    # thread-name metadata lanes for every emitting thread
    meta = [e for e in doc["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"]
    assert {e["tid"] for e in meta} >= {e["tid"] for e in xs}
    # spans NEST per thread: input.wait lies within train.step's window
    # on the same tid; the other thread's span has a different tid
    by_name = {e["name"]: e for e in xs}
    outer, inner = by_name["train.step"], by_name["input.wait"]
    assert inner["tid"] == outer["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1.0
    assert by_name["input.stage"]["tid"] != outer["tid"]


def test_ring_bound_is_honored():
    rec = FlightRecorder(ring=64)
    for _ in range(500):
        with rec.span("train.step"):
            pass
    assert len(rec) == 64
    assert sum(1 for e in rec.trace_events() if e["ph"] == "X") == 64


def test_disabled_recorder_records_nothing():
    rec = FlightRecorder(ring=64, enabled=False)
    with rec.span("train.step"):
        pass
    assert len(rec) == 0


def test_unknown_span_warns_but_records(caplog):
    rec = FlightRecorder(ring=16)
    with rec.span("totally.unregistered.span"):  # shardcheck: ok(registry-drift)
        pass
    assert len(rec) == 1


def test_dump_without_configuration_is_a_noop():
    rec = FlightRecorder(ring=16)
    assert rec.dump(reason="x") is None  # no dump dir known — never raises


def test_span_catalog_covers_every_emitted_literal():
    """Every span name the package emits resolves in SPAN_CATALOG (the
    registry-drift rule enforces it repo-wide; this pins the catalog
    against accidental deletion) and trace_dump/goodput are registered
    events."""
    assert "goodput" in EVENT_SCHEMAS and "trace_dump" in EVENT_SCHEMAS
    for name in ("input.wait", "train.step", "eval.round",
                 "checkpoint.save", "serve.batch", "restore"):
        assert name in SPAN_CATALOG


# ---------------------------------------------------------------------------
# goodput classification (fake clock)
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_goodput_interval_classifies_and_sums_to_100():
    clock = FakeClock()
    m = GoodputMeter(clock=clock)
    m.rebase()
    clock.t += 10.0
    m.add("input_wait", 2.0)
    m.add("checkpoint", 1.0)
    m.add("eval", 0.5)
    itv = m.interval()
    assert itv["wall_secs"] == 10.0
    assert itv["seconds"]["compute"] == pytest.approx(6.5)
    assert itv["seconds"]["input_wait"] == pytest.approx(2.0)
    assert set(itv["pct"]) == set(CATEGORIES)
    assert sum(itv["pct"].values()) == pytest.approx(100.0, abs=0.1)
    # the next interval starts fresh
    clock.t += 4.0
    m.add("stall", 4.0)
    itv2 = m.interval()
    assert itv2["seconds"]["compute"] == pytest.approx(0.0)
    assert itv2["seconds"]["stall"] == pytest.approx(4.0)
    assert itv2["seconds"]["input_wait"] == pytest.approx(0.0)


def test_goodput_overmeasured_interval_normalizes():
    """Charges exceeding the wall (a second thread charging the same
    window) clamp compute at 0 and normalize pct over the measured sum —
    never >100% total."""
    clock = FakeClock()
    m = GoodputMeter(clock=clock)
    m.rebase()
    clock.t += 5.0
    m.add("checkpoint", 8.0)
    itv = m.interval()
    assert itv["seconds"]["compute"] == 0.0
    assert sum(itv["pct"].values()) == pytest.approx(100.0, abs=0.1)


def test_goodput_first_interval_without_rebase_is_empty():
    m = GoodputMeter(clock=FakeClock())
    itv = m.interval()
    assert itv["wall_secs"] == 0.0


def test_nested_categorized_spans_charge_outermost_only():
    before = goodput.snapshot()
    with recorder.span("eval.round", category="eval"):
        with recorder.span("input.wait", category="input_wait"):
            time.sleep(0.002)
        time.sleep(0.002)
    after = goodput.snapshot()
    assert after.get("eval", 0) > before.get("eval", 0)
    # the inner categorized span charged NOTHING (outermost-span rule)
    assert after.get("input_wait", 0) == pytest.approx(
        before.get("input_wait", 0))


def test_goodput_hook_emits_registered_event(tmp_path):
    from distributed_resnet_tensorflow_tpu.train.hooks import GoodputHook
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = GoodputHook(w, every_steps=10)
    hook.reset_window()
    goodput.add("input_wait", 0.001)
    time.sleep(0.005)
    hook(10, None, {})
    w.close()
    rows = [r for r in read_metrics(str(tmp_path))
            if r.get("event") == "goodput"]
    assert rows, "no goodput row emitted"
    row = rows[-1]
    assert row["step"] == 10
    assert set(row["pct"]) == set(CATEGORIES)
    assert sum(row["pct"].values()) == pytest.approx(100.0, abs=0.5)


# ---------------------------------------------------------------------------
# metrics.jsonl rotation
# ---------------------------------------------------------------------------

def test_metrics_rotation_bounds_size_and_reads_in_order(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False,
                      max_bytes=600, max_segments=3)
    for i in range(60):
        w.write_scalars(i, {"loss": float(i)})
    w.close()
    base = os.path.join(str(tmp_path), "metrics.jsonl")
    segs = sorted(glob.glob(base + ".*"))
    assert segs, "no rotation happened"
    assert len(segs) <= 3
    # every file honors the bound (±1 row slack by construction)
    for p in segs + [base]:
        assert os.path.getsize(p) <= 600 + 120
    rows = read_metrics(str(tmp_path))
    steps = [r["step"] for r in rows]
    # one continuous, ordered stream ending at the newest row; the oldest
    # rows beyond the segment budget are gone
    assert steps == sorted(steps)
    assert steps[-1] == 59
    assert len(set(steps)) == len(steps)


def test_read_metrics_tolerant_skips_torn_tail(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    w.write_scalars(1, {"loss": 1.0})
    w.close()
    with open(os.path.join(str(tmp_path), "metrics.jsonl"), "a") as f:
        f.write('{"step": 2, "loss"')  # torn mid-write
    with pytest.raises(ValueError):
        read_metrics(str(tmp_path))
    rows = read_metrics(str(tmp_path), tolerant=True)
    assert [r["step"] for r in rows] == [1]


def test_rotation_off_by_default_threshold_not_hit(tmp_path):
    w = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    for i in range(20):
        w.write_scalars(i, {"loss": 0.0})
    w.close()
    assert not glob.glob(os.path.join(str(tmp_path), "metrics.jsonl.*"))
    assert len(read_metrics(str(tmp_path))) == 20


# ---------------------------------------------------------------------------
# decode-process stage-counter ship-back (satellite)
# ---------------------------------------------------------------------------

def test_stage_stats_worker_merge_keeps_busiest_worker_honest():
    s = StageStats()
    s.add("decode", 2.0, items=10, worker=("decode-proc", 0))
    s.add("decode", 3.0, items=20, worker=("decode-proc", 1))
    s.add("decode", 1.0, items=5, worker=("decode-proc", 0))
    snap = s.snapshot()["decode"]
    assert snap["workers"] == 2
    assert snap["items"] == 35
    assert snap["seconds"] == pytest.approx(6.0)
    # busiest worker = proc0's 3.0 cumulative, not the 6.0 sum
    assert snap["max_thread_seconds"] == pytest.approx(3.0)


def _jpeg_bytes(size=48):
    import io

    from PIL import Image
    img = np.random.RandomState(0).randint(0, 256, (size, size, 3), np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG")
    return buf.getvalue()


def test_decode_loop_process_mode_ships_counter_deltas():
    """Process-mode _decode_loop (stop=None) must put _StageDelta rows on
    the result queue BEFORE its _END marker — the parent stops consuming
    at the n-th _END, so a later delta would be lost."""
    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        _decode_loop, _END, _EndMarker, _StageDelta)
    jpeg = _jpeg_bytes()
    in_q, out_q = queue.Queue(), queue.Queue()
    for _ in range(3):
        in_q.put((jpeg, 1))
    in_q.put(_END)
    _decode_loop(in_q, out_q, wseed=0, is_train=False, image_size=32,
                 native_decode=False, emit_uint8=True, stop=None, widx=7)
    items = []
    while not out_q.empty():
        items.append(out_q.get_nowait())
    deltas = [i for i in items if isinstance(i, _StageDelta)]
    ends = [i for i, it in enumerate(items) if isinstance(it, _EndMarker)]
    assert deltas and sum(d.count for d in deltas) == 3
    assert all(d.widx == 7 for d in deltas)
    assert all(d.seconds > 0 for d in deltas)
    delta_idx = [i for i, it in enumerate(items)
                 if isinstance(it, _StageDelta)]
    assert max(delta_idx) < min(ends), "delta after _END would be dropped"


def test_decode_process_counters_merge_into_parent_registry(tmp_path):
    """E2E: decode_processes > 0 leaves decode busy-time in the PARENT's
    input_stages — the attribution gap this satellite closes."""
    from test_imagenet_data import _write_fake_imagenet

    from distributed_resnet_tensorflow_tpu.data.imagenet import (
        imagenet_iterator)
    from distributed_resnet_tensorflow_tpu.utils.metrics import input_stages
    d, total = _write_fake_imagenet(tmp_path, mode="validation")
    input_stages.reset()
    it = imagenet_iterator(d, batch_size=5, mode="eval", image_size=32,
                           decode_processes=1)
    n = 0
    for b in it:
        mask = b.get("mask", np.ones(len(b["labels"])))
        n += int(mask.sum())
    assert n == total
    snap = input_stages.snapshot()
    assert "decode" in snap, "no decode counters merged from the worker"
    assert snap["decode"]["items"] == total
    assert snap["decode"]["seconds"] > 0


# ---------------------------------------------------------------------------
# cluster monitor
# ---------------------------------------------------------------------------

def _write_stream(d, rows):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "metrics.jsonl"), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_monitor_aggregates_two_host_streams(tmp_path):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import aggregate
    now = 1000.0
    _write_stream(str(tmp_path / "host0" / "train"), [
        {"step": 100, "time": now - 20, "loss": 2.0},
        {"step": 200, "time": now - 10, "loss": 1.5},
        {"event": "goodput", "time": now - 10, "step": 200,
         "wall_secs": 10.0,
         "seconds": {c: 0.0 for c in CATEGORIES},
         "pct": {"compute": 80.0, "input_wait": 20.0, "checkpoint": 0.0,
                 "eval": 0.0, "stall": 0.0, "restart": 0.0}},
    ])
    _write_stream(str(tmp_path / "host1" / "train"), [
        {"step": 100, "time": now - 20, "loss": 2.1},
        {"step": 150, "time": now - 10, "loss": 1.9},
    ])
    hb = tmp_path / "heartbeats"
    hb.mkdir()
    for pid, step in ((0, 200), (1, 150)):
        (hb / f"proc{pid}.json").write_text(json.dumps({
            "process_id": pid, "pid": 10 + pid, "host": f"h{pid}",
            "seq": 9, "step": step, "progress": step, "phase": "train",
            "wall_time": now - 1}))
    agg = aggregate(str(tmp_path), now=now)
    assert set(agg["streams"]) == {os.path.join("host0", "train"),
                                   os.path.join("host1", "train")}
    s0 = agg["streams"][os.path.join("host0", "train")]
    assert s0["step"] == 200
    assert s0["steps_per_sec"] == pytest.approx(10.0)
    assert s0["goodput_pct"] == pytest.approx(80.0)
    s1 = agg["streams"][os.path.join("host1", "train")]
    assert s1["steps_per_sec"] == pytest.approx(5.0)
    # cluster headline: the fastest (chief) stream leads
    assert agg["steps_per_sec"] == pytest.approx(10.0)
    assert agg["goodput"]["compute"] == pytest.approx(80.0)
    assert set(agg["hosts"]) == {"0", "1"}
    assert agg["host_step_skew"] == 50
    assert "stale_hosts" not in agg


def test_monitor_once_json_cli(tmp_path, capsys):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import (
        main_monitor, render)
    _write_stream(str(tmp_path / "train"), [
        {"step": 10, "time": time.time() - 5, "loss": 1.0},
        {"step": 20, "time": time.time(), "loss": 0.9},
    ])
    rc = main_monitor(["--root", str(tmp_path), "--once", "--json"])
    assert rc == 0
    agg = json.loads(capsys.readouterr().out)
    assert "train" in agg["streams"]
    assert agg["streams"]["train"]["step"] == 20
    # the text renderer stays crash-free on the same aggregate
    assert "drt monitor" in render(agg)


def test_monitor_tolerates_torn_and_empty_streams(tmp_path):
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import aggregate
    d = tmp_path / "train"
    d.mkdir(parents=True)
    (d / "metrics.jsonl").write_text('{"step": 1, "time": 1.0}\n{"torn')
    agg = aggregate(str(tmp_path))
    assert agg["streams"]["train"]["step"] == 1


def test_monitor_fleet_rollup_spans_rotation_mid_ladder(tmp_path):
    """A rotation landing in the middle of a replace ladder (kill/respawn
    in the rotated segment, readmit in the live file, plus a torn tail)
    must not lose the ladder: the fleet rollup surfaces the newest rung
    and the joined stream replays protocol-conformant (ISSUE 20)."""
    from distributed_resnet_tensorflow_tpu.analysis.protocol import (
        check_stream)
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import aggregate
    now = 1000.0
    d = tmp_path / "route"
    d.mkdir(parents=True)
    rotated = [
        {"event": "route", "time": now - 30, "requests": 500,
         "completed": 480, "errors": 0, "shed": 0, "qps": 25.0,
         "p99_ms": 40.0},
        {"event": "replica_health", "time": now - 21, "replica": 0,
         "from": "ready", "to": "dead", "reason": "beat_stale"},
        {"event": "replica_replace", "time": now - 20, "replica": 0,
         "action": "kill", "reason": "wedged"},
        {"event": "replica_replace", "time": now - 15, "replica": 0,
         "action": "respawn"},
    ]
    live = [
        {"event": "replica_replace", "time": now - 5, "replica": 0,
         "action": "readmit"},
        {"event": "replica_health", "time": now - 4, "replica": 0,
         "from": "dead", "to": "warming", "reason": "readmit"},
        {"event": "route", "time": now - 1, "requests": 600,
         "completed": 575, "errors": 1, "shed": 0, "qps": 26.0,
         "p99_ms": 41.0},
    ]
    (d / "metrics.jsonl.1").write_text(
        "".join(json.dumps(r) + "\n" for r in rotated))
    (d / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in live)
        + '{"event": "replica_re')                    # torn mid-write
    agg = aggregate(str(tmp_path), now=now)
    fleet = agg["fleet"]
    assert fleet["requests"] == 600                   # live file leads
    assert fleet["replica_replace"]["action"] == "readmit"
    assert fleet["replica_replace"]["replica"] == 0
    # the ladder that spans the rotation replays as ONE legal round
    assert check_stream(str(d / "metrics.jsonl")) == []


def test_monitor_elastic_rollup_spans_rotation_mid_round(tmp_path):
    """A reshard round split by rotation (the reshard row in the rotated
    segment, the new generation's mesh row in the live file): the
    elastic rollup sees generation + reason, and the step rate bridges
    the rotation boundary instead of resetting."""
    from distributed_resnet_tensorflow_tpu.analysis.protocol import (
        check_stream)
    from distributed_resnet_tensorflow_tpu.telemetry.monitor import aggregate
    now = 1000.0
    d = tmp_path / "train"
    d.mkdir(parents=True)
    rotated = [
        {"step": 80, "time": now - 20, "loss": 2.0},
        {"step": 90, "time": now - 15, "loss": 1.9},
        {"event": "reshard", "time": now - 12, "generation": 2,
         "reason": "peer_lost", "old_hosts": 2, "new_hosts": 1,
         "restore_step": 90},
    ]
    live = [
        {"event": "mesh_generation", "time": now - 8, "generation": 2,
         "hosts": 1, "devices": 8, "step": 90},
        {"step": 110, "time": now - 5, "loss": 1.8},
        {"step": 120, "time": now, "loss": 1.7},
    ]
    (d / "metrics.jsonl.1").write_text(
        "".join(json.dumps(r) + "\n" for r in rotated))
    (d / "metrics.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in live)
        + '{"step": 121, "ti')                        # torn mid-write
    agg = aggregate(str(tmp_path), now=now)
    assert agg["mesh_generation"] == 2
    assert agg["last_reshard"]["reason"] == "peer_lost"
    assert agg["last_reshard"]["new_hosts"] == 1
    s = agg["streams"]["train"]
    assert s["step"] == 120
    # (120 - 80) steps over 20 s across the rotation boundary
    assert s["steps_per_sec"] == pytest.approx(2.0)
    assert check_stream(str(d / "metrics.jsonl")) == []


# ---------------------------------------------------------------------------
# watchdog anomaly hook
# ---------------------------------------------------------------------------

def test_watchdog_escalation_dumps_flight_record(tmp_path):
    """A hang escalation must leave trace.json + a trace_dump metrics row
    + a goodput stall charge — the automatic flight-recorder contract
    (the live 2-process frozen-peer path is scripts/chaos_smoke.sh)."""
    from distributed_resnet_tensorflow_tpu.resilience.heartbeat import (
        HeartbeatPublisher, BeatTransport)
    from distributed_resnet_tensorflow_tpu.resilience.watchdog import Watchdog
    from distributed_resnet_tensorflow_tpu.utils.config import WatchdogConfig

    class NullTransport(BeatTransport):
        def publish(self, beat):
            pass

        def peers(self):
            return {}

    dump_dir = str(tmp_path / "telemetry")
    stub = FakeWriter()
    recorder.configure(dump_dir=dump_dir, writer=stub, process_index=0)
    try:
        with recorder.span("train.step"):
            pass
        clock = FakeClock()
        publisher = HeartbeatPublisher(NullTransport(), 0, clock=clock)
        publisher.update(step=3, phase="train")
        stall_before = goodput.snapshot().get("stall", 0.0)
        clock.t += 42.0
        wd = Watchdog(NullTransport(), publisher, 0, 2,
                      WatchdogConfig(), writer=FakeWriter(),
                      clock=clock, exit_fn=lambda code: None)
        wd._escalate("hang", 75, "no progress for 42s", now=clock.t)
        path = os.path.join(dump_dir, "trace.json")
        assert os.path.exists(path)
        doc = json.load(open(path))
        assert doc["otherData"]["reason"] == "hang"
        assert any(e.get("name") == "train.step"
                   for e in doc["traceEvents"])
        dumps = [e for e in stub.events if e["event"] == "trace_dump"]
        assert dumps and dumps[0]["reason"] == "hang"
        assert dumps[0]["span_schema_version"] == SPAN_SCHEMA_VERSION
        assert goodput.snapshot()["stall"] - stall_before == \
            pytest.approx(42.0)
    finally:
        recorder._writer = None  # don't leak the stub into other tests
