"""VGG-style ImageNet preprocessing — numpy/PIL re-expression.

Parity with reference vgg_preprocessing.py:
  * train: resize shorter side to a random scale in [256, 512]
    (reference :284-314), random 224x224 crop (reference _random_crop:88),
    random horizontal flip, RGB mean subtraction with means scaled to the
    [0,1] pixel range (reference :37-39: _R_MEAN=123.68/255 etc.)
  * eval: resize shorter side to 256, central 224x224 crop
    (reference preprocess_for_eval:317-333)

Decoding + resizing happen on the host (PIL), the cheap float ops in numpy;
the TPU sees ready, fixed-shape float32 NHWC batches.
"""
from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np

# reference vgg_preprocessing.py:37-39 (means already divided by 255)
R_MEAN = 123.68 / 255.0
G_MEAN = 116.78 / 255.0
B_MEAN = 103.94 / 255.0
RGB_MEANS = np.asarray([R_MEAN, G_MEAN, B_MEAN], np.float32)

RESIZE_SIDE_MIN = 256   # reference vgg_preprocessing.py:41-42
RESIZE_SIDE_MAX = 512
DEFAULT_IMAGE_SIZE = 224


def decode_jpeg(data: bytes) -> np.ndarray:
    """JPEG/PNG bytes → RGB uint8 HWC."""
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return np.asarray(img, np.uint8)


def encode_jpeg(image: np.ndarray, quality: int = 90) -> bytes:
    """RGB uint8 HWC → JPEG bytes (test fixtures / dataset tooling)."""
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(image, "RGB").save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _aspect_preserving_resize(image: np.ndarray, smaller_side: int) -> np.ndarray:
    """reference _aspect_preserving_resize:259-281."""
    from PIL import Image
    h, w = image.shape[:2]
    scale = smaller_side / min(h, w)
    nh, nw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
    if (nh, nw) == (h, w):
        return image
    out = Image.fromarray(image).resize((nw, nh), Image.BILINEAR)
    return np.asarray(out, np.uint8)


def preprocess_for_train(image: np.ndarray, rng: np.random.RandomState,
                         output_size: int = DEFAULT_IMAGE_SIZE,
                         resize_side_min: int = RESIZE_SIDE_MIN,
                         resize_side_max: int = RESIZE_SIDE_MAX) -> np.ndarray:
    """reference preprocess_for_train:284-314."""
    side = rng.randint(resize_side_min, resize_side_max + 1)
    image = _aspect_preserving_resize(image, side)
    h, w = image.shape[:2]
    top = rng.randint(0, h - output_size + 1)
    left = rng.randint(0, w - output_size + 1)
    crop = image[top:top + output_size, left:left + output_size]
    if rng.rand() < 0.5:
        crop = crop[:, ::-1]
    return crop.astype(np.float32) / 255.0 - RGB_MEANS


def preprocess_for_eval(image: np.ndarray,
                        output_size: int = DEFAULT_IMAGE_SIZE,
                        resize_side: int = RESIZE_SIDE_MIN) -> np.ndarray:
    """reference preprocess_for_eval:317-333."""
    image = _aspect_preserving_resize(image, resize_side)
    h, w = image.shape[:2]
    top = (h - output_size) // 2
    left = (w - output_size) // 2
    crop = image[top:top + output_size, left:left + output_size]
    return crop.astype(np.float32) / 255.0 - RGB_MEANS


def preprocess_image(image: np.ndarray, is_training: bool,
                     rng: Optional[np.random.RandomState] = None,
                     output_size: int = DEFAULT_IMAGE_SIZE) -> np.ndarray:
    """reference preprocess_image:336-363 dispatch."""
    if is_training:
        return preprocess_for_train(image, rng or np.random.RandomState(),
                                    output_size)
    return preprocess_for_eval(image, output_size)
