from .manager import (CheckpointManager, poll_new_checkpoint,  # noqa: F401
                      wait_for_new_checkpoint)
