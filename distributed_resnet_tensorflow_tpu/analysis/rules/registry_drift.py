"""event-registry + span-catalog + config-knob: names must resolve.

Three drift checks against the project's declared registries:

  * every ``write_event("<name>", ...)`` literal in code and every
    ``{"event": "<name>"}`` mention in docs/scripts must be declared in
    ``utils.metrics.EVENT_SCHEMAS`` — the one source of truth for the
    metrics.jsonl event stream;
  * every ``span("<name>")`` literal passed to the flight-recorder tracer
    (and every ``span("<name>")`` mention in docs/scripts) must be
    declared in ``telemetry.tracer.SPAN_CATALOG`` — trace.json consumers
    and the goodput classifier key on these names, so an unregistered
    span is invisible drift exactly like an unregistered event;
  * every ``--set a.b.c=`` knob referenced in code, scripts or docs must
    resolve against the ``utils.config.ExperimentConfig`` dataclasses —
    the knob a README advertises must actually exist (``cfg.override``
    raises at runtime, but docs and sbatch scripts never run under CI).

All catch the "renamed it in code, forgot the docs/launcher" class that
otherwise surfaces as a crashed job after a 20-minute queue wait.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from typing import Iterable

from ..report import Finding

RULE_NAME = "registry-drift"
DOC = __doc__

# documentation placeholders, not real knobs ("--set k=v", "--set
# dotted.path=value" in usage strings)
_KNOB_PLACEHOLDERS = {"k", "key", "KEY", "a.b.c", "dotted.path", "x.y.z"}

# two reference shapes: a concrete override (requires the trailing "=" so
# usage prose like "--set expects KEY=VALUE" stays quiet) and a wildcard
# section reference ("--set resilience.watchdog.*", no "=" required)
_KNOB_RE = re.compile(
    r'--set[\s"=]+(?:([A-Za-z_][\w.]*\.\*)|([A-Za-z_][\w.]*)\s*=)')
_DOC_EVENT_RE = re.compile(r'"event"\s*:\s*"(\w+)"')
# span-name mentions in docs/scripts: span("input.wait") / ``span("x.y")``
_DOC_SPAN_RE = re.compile(r'span\(\s*"([\w.]+)"')


def _event_names() -> set:
    from ...utils.metrics import EVENT_SCHEMAS
    return set(EVENT_SCHEMAS)


def _span_names() -> set:
    from ...telemetry.tracer import SPAN_CATALOG
    return set(SPAN_CATALOG)


def _knob_resolves(dotted: str) -> bool:
    from ...utils.config import ExperimentConfig
    cur = ExperimentConfig()
    for part in dotted.split("."):
        if part == "*":
            # wildcard tail ("resilience.watchdog.*") — the prefix must be
            # a config section (dataclass), not a leaf
            return dataclasses.is_dataclass(cur)
        if not dataclasses.is_dataclass(cur) or not hasattr(cur, part):
            return False
        cur = getattr(cur, part)
    return True


def _is_write_event(node: ast.Call) -> bool:
    fn = node.func
    return isinstance(fn, ast.Attribute) and \
        fn.attr in ("write_event", "_write_event")


def _is_span_call(node: ast.Call) -> bool:
    """``span("...")`` (the module-level convenience) or
    ``recorder.span("...")`` — the two spellings the tracer exports.
    Deliberately NOT any ``<obj>.span(...)``: an unrelated API named span
    (e.g. a regex match group helper) must not turn the gate red."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "span":
        return True
    return isinstance(fn, ast.Attribute) and fn.attr == "span" and \
        isinstance(fn.value, ast.Name) and fn.value.id == "recorder"


def check(ctx) -> Iterable[Finding]:
    events = _event_names()
    spans = _span_names()

    # (a) write_event + span literals in python
    for sf in ctx.all_python():
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            arg = node.args[0]
            if not (isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)):
                continue
            if _is_write_event(node) and arg.value not in events:
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    f"metrics event {arg.value!r} is not declared in "
                    "utils.metrics.EVENT_SCHEMAS — register it there "
                    "first")
            elif _is_span_call(node) and arg.value not in spans:
                yield Finding(
                    RULE_NAME, sf.rel, node.lineno,
                    f"tracer span {arg.value!r} is not declared in "
                    "telemetry.tracer.SPAN_CATALOG — register it there "
                    "first")

    # (b) {"event": "<name>"} and span("<name>") mentions in docs + scripts
    for sf in ctx.docs + ctx.scripts:
        for i, line in enumerate(sf.lines, 1):
            for m in _DOC_EVENT_RE.finditer(line):
                if m.group(1) not in events:
                    yield Finding(
                        RULE_NAME, sf.rel, i,
                        f"documented metrics event {m.group(1)!r} does not "
                        "exist in utils.metrics.EVENT_SCHEMAS — stale doc "
                        "or missing registration")
            for m in _DOC_SPAN_RE.finditer(line):
                if m.group(1) not in spans:
                    yield Finding(
                        RULE_NAME, sf.rel, i,
                        f"documented tracer span {m.group(1)!r} does not "
                        "exist in telemetry.tracer.SPAN_CATALOG — stale "
                        "doc or missing registration")

    # (c) --set knob references everywhere
    for sf in ctx.all_python() + ctx.scripts + ctx.docs:
        for i, line in enumerate(sf.lines, 1):
            for m in _KNOB_RE.finditer(line):
                knob = m.group(1) or m.group(2)
                if knob in _KNOB_PLACEHOLDERS:
                    continue
                if not _knob_resolves(knob):
                    yield Finding(
                        RULE_NAME, sf.rel, i,
                        f"--set {knob}=... does not resolve against the "
                        "ExperimentConfig dataclasses (utils/config.py) — "
                        "typo or renamed knob")
