#!/bin/bash
# SLURM submit shim — successor of the reference's per-(machine x dataset x
# backend) submit scripts (reference scripts/submit_cifar_daint_dist.sh etc.,
# SURVEY.md §2.19). One script: preset + overrides come from the command line.
#
#   sbatch -N <nodes> scripts/submit_tpu_slurm.sh <preset> [--set k=v ...]
#
# Every task runs the same SPMD program; parallel/distributed.py derives
# (coordinator, num_processes, process_id) from SLURM_* env vars — the ~200
# lines of host-list bash from the reference launcher are gone.
#SBATCH --job-name=drt-tpu
#SBATCH --ntasks-per-node=1
#SBATCH --time=12:00:00

set -euo pipefail

PRESET="${1:-cifar10_resnet50}"
shift || true

LOG_ROOT="${LOG_ROOT:-logs/${SLURM_JOB_NAME:-drt}-${SLURM_JOB_ID:-local}}"
mkdir -p "$LOG_ROOT"

# Shardcheck gate (scripts/analysis_gate.sh): catch PartitionSpec /
# config / invariant bugs in ~1 CPU-minute at allocation start, before
# minutes of XLA compile + a step-1 crash burn the whole multi-node
# allocation. (This batch script runs AFTER the queue wait — to spend
# zero allocation time on a doomed config, run scripts/analysis_gate.sh
# on the submit host before sbatch; this in-job gate is the backstop.)
# Runs on the FIRST submission only — a preemption requeue re-enters this
# script with the code already vetted, and the gate's virtual-CPU jax
# startup would only delay the resume. SKIP_ANALYSIS_GATE=1 escapes a
# broken submit host's python env.
# NOTE: under sbatch, $0 is the SPOOLED copy of this script (slurmd spool
# dir) — resolve the gate from the submit directory like every other
# relative path here (LOG_ROOT), falling back to $0's dir for direct runs.
GATE="${SLURM_SUBMIT_DIR:-$(dirname "$0")/..}/scripts/analysis_gate.sh"
if [[ "${SLURM_RESTART_COUNT:-0}" == "0" && "${SKIP_ANALYSIS_GATE:-0}" != "1" ]]; then
  if [[ -x "$GATE" ]]; then
    "$GATE" || {
      echo "shardcheck gate failed — fix the findings (or rerun with" \
           "SKIP_ANALYSIS_GATE=1 if the gate itself is broken)"
      exit 1
    }
  else
    echo "shardcheck gate not found at $GATE — skipping (submit from the" \
         "repo root for pre-run checking)"
  fi
fi

# reference parity: optional checkpoint wipe via FRESH=1
# (reference submit_cifar_daint_dist.sh:67-73). Guarded by
# SLURM_RESTART_COUNT: a requeue after preemption re-runs this script with
# the ORIGINAL submission environment (FRESH=1 included) — wiping then
# would delete the preemption checkpoint the requeue exists to resume from
if [[ "${FRESH:-0}" == "1" && "${SLURM_RESTART_COUNT:-0}" == "0" ]]; then
  rm -rf "$LOG_ROOT/ckpt"
fi

# Exit-code contract (docs/resilience.md): 75 (EX_TEMPFAIL) means the run
# stopped resumable — graceful preemption, or the health watchdog tore it
# down after peer loss / a hung collective — with a committed checkpoint to
# resume from. Requeue the job instead of failing it; any other nonzero
# code is a real error.
set +e
srun --no-kill python -m distributed_resnet_tensorflow_tpu.main \
  --preset "$PRESET" \
  --set "log_root=$LOG_ROOT" \
  "$@"
rc=$?
set -e

# srun reports the HIGHEST task code. 137/143 (SIGKILL/SIGTERM death) is
# the host-loss / OOM-kill shape: the surviving tasks exited 75 via their
# watchdogs but the killed task's code wins the max — requeue those too
# (MAX_REQUEUES bounds a genuinely crash-looping job).
if [[ $rc -eq 137 || $rc -eq 143 ]]; then
  echo "task killed by signal (exit $rc): treating as host loss, requeueing"
  rc=75
fi

if [[ $rc -eq 75 ]]; then
  # CAVEAT: srun reports the HIGHEST task exit code, so one task failing
  # with a small code (e.g. 1) while peers exit 75 is masked as "preempted"
  # — MAX_REQUEUES bounds the damage if that job is genuinely broken
  if [[ "${SLURM_RESTART_COUNT:-0}" -ge "${MAX_REQUEUES:-20}" ]]; then
    echo "exit 75 but MAX_REQUEUES (${MAX_REQUEUES:-20}) reached; failing"
    exit 1
  fi
  echo "run preempted (exit 75): checkpoint committed, requeueing for resume"
  if [[ -n "${SLURM_JOB_ID:-}" ]] && scontrol requeue "$SLURM_JOB_ID"; then
    exit 0
  fi
  # outside SLURM (or requeue refused): surface the resumable code so a
  # wrapper loop can relaunch
  exit 75
fi
exit $rc
