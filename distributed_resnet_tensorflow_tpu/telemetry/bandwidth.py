"""Per-fabric achieved-bandwidth catalog (``results/bandwidth/<fabric>.json``).

``parallel/overlap.probe_comm_plan`` measures what each planned exchange
bucket's collective actually achieves on the live mesh — but until now
that measurement died with the run: ``main.py comm-report`` needed a
fresh probe and the what-if planner (telemetry/planner.py) had nothing
measured to cost candidate layouts against. This module persists every
probe into a small per-fabric catalog keyed by the reduce-axis set, so
any later process on the same fabric can read achieved bytes/sec without
holding a live mesh.

A *fabric* is the hardware the numbers are valid for: platform ×
device kind × global device count (``fabric_id``) — a v4-32's ICI numbers
must never cost a v5e-8 plan, and the virtual-8 CPU mesh the tests/gate
run on gets its own file.

Catalog schema (``schema_version`` 2, documented in docs/planner.md)::

    {
     "schema_version": 2,
     "fabric": "cpu-8",            # fabric_id() of the measuring run
     "platform": "cpu",
     "device_kind": "cpu",
     "devices": 8,
     "axes": {                     # keyed by the probe's reduce-axis set
      "data+fsdp": {
       "bytes_per_sec": 4.1e8,     # best standalone WIRE bytes/sec seen
       "latency_secs": 2.3e-4,     # smallest per-collective cost seen
       "samples": 12,              # probe buckets folded in, ever
       "min_wire_bytes": 20480,    # payload range the numbers came from
       "max_wire_bytes": 4194304
      },
      "data+fsdp:intra": {         # hierarchical tier rows (v2): the
       "tier": "intra",            # probe's grouped-psum legs over the
       ...                         # fast intra-host / slow inter-host
      }, ...                       # sub-groups of the data axis — what
     }                             # tune_comm_plan ranks hierarchy with
    }

v1 documents (no tier rows, no ``tier`` field) load unchanged — every
v1 key is a valid v2 flat key; the first probe fold on a factored mesh
adds the tier rows and stamps the current schema_version.

Merging is best-achieved: ``bytes_per_sec`` only ratchets up and
``latency_secs`` only down — the probe times collectives standalone
(best-of-reps), so the catalog is the fabric's demonstrated ceiling, the
right operand for a planner that predicts what a layout *could* do.
Writes are atomic (tmp + ``os.replace``) and never raise: losing one
probe's persistence must not kill training.
"""
from __future__ import annotations

import json
import logging
import os
import re
from typing import Dict, List, Optional

log = logging.getLogger(__name__)

SCHEMA_VERSION = 2

#: env override for the catalog directory (tests point it at a tmpdir;
#: multi-user clusters point it at a shared results tree)
DIR_ENV = "DRT_BANDWIDTH_DIR"


def catalog_dir() -> str:
    override = os.environ.get(DIR_ENV)
    if override:
        return override
    repo_root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(repo_root, "results", "bandwidth")


def fabric_id(devices=None) -> str:
    """``<platform>-<n>`` (plus the device kind when it says more than
    the platform does): the key deciding which catalog file a
    measurement lands in / a prediction reads from."""
    if devices is None:
        import jax
        devices = jax.devices()
    d0 = devices[0]
    platform = str(getattr(d0, "platform", "unknown")).lower()
    kind = str(getattr(d0, "device_kind", "") or "").lower()
    parts = [platform]
    if kind and kind != platform:
        parts.append(kind)
    parts.append(str(len(devices)))
    return re.sub(r"[^a-z0-9.]+", "-", "-".join(parts)).strip("-")


def catalog_path(fabric: Optional[str] = None) -> str:
    return os.path.join(catalog_dir(), f"{fabric or fabric_id()}.json")


def load_catalog(path: Optional[str] = None,
                 fabric: Optional[str] = None) -> Optional[dict]:
    """The catalog document, or None when absent/unreadable (callers
    fall back to the planner's reference table / a live probe)."""
    path = path or catalog_path(fabric)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        log.debug("bandwidth catalog unreadable at %s (%s)", path, e)
        return None
    if not isinstance(doc, dict) or not isinstance(doc.get("axes"), dict):
        log.warning("bandwidth catalog at %s is malformed; ignoring", path)
        return None
    return doc


def lookup(catalog: Optional[dict], axes_sig: str) -> Optional[dict]:
    """The axes entry for a reduce-axis signature (``"data+fsdp"``),
    falling back to the entry sharing the most axis names (a dp_tp
    prediction on a fabric only probed under dp still gets the measured
    order of magnitude rather than nothing). Deterministic: ties break
    on the entry name."""
    if not catalog:
        return None
    axes = catalog.get("axes", {})
    entry = axes.get(axes_sig)
    if entry is not None:
        return entry
    base, _, tier = axes_sig.partition(":")
    if tier:
        # tiered query without a tiered row: the flat row for the same
        # axis set is the honest stand-in
        entry = axes.get(base)
        if entry is not None:
            return entry
    want = set(base.split("+"))
    best = None
    for name in sorted(axes):
        nbase, _, ntier = name.partition(":")
        overlap = len(want & set(nbase.split("+")))
        key = (overlap, 1 if ntier == tier else 0,
               axes[name].get("samples", 0))
        if best is None or key > best[0]:
            best = (key, axes[name])
    return best[1] if best else None


def update_from_probe(snapshot: Optional[dict],
                      path: Optional[str] = None,
                      devices=None) -> Optional[str]:
    """Fold one ``probe_comm_plan`` snapshot (``utils.metrics.
    comm_timing_stats`` shape: per-bucket wire bytes / probe secs /
    axes) into the fabric's catalog. Returns the path written, or None
    when there was nothing to record / the write failed (logged, never
    raised — persistence is observability, not correctness)."""
    if not snapshot or not snapshot.get("buckets"):
        return None
    try:
        if devices is None:
            import jax
            devices = jax.devices()
        fabric = fabric_id(devices)
        path = path or catalog_path(fabric)
        doc = load_catalog(path) or {
            "schema_version": SCHEMA_VERSION,
            "fabric": fabric,
            "platform": str(getattr(devices[0], "platform", "unknown")),
            "device_kind": str(getattr(devices[0], "device_kind", "")),
            "devices": len(devices),
            "axes": {},
        }
        # folding under the current schema: v1 docs carry only flat keys,
        # all valid under v2 — stamp the version forward on write
        doc["schema_version"] = SCHEMA_VERSION
        axes: Dict[str, dict] = doc.setdefault("axes", {})

        def fold(sig, wire, bw, secs, tier=None):
            if wire <= 0 or bw <= 0 or secs <= 0:
                return
            e = axes.get(sig)
            if e is None:
                e = axes[sig] = {"bytes_per_sec": bw,
                                 "latency_secs": secs,
                                 "samples": 1, "min_wire_bytes": wire,
                                 "max_wire_bytes": wire}
            else:
                e["bytes_per_sec"] = max(float(e["bytes_per_sec"]), bw)
                e["latency_secs"] = min(float(e["latency_secs"]), secs)
                e["samples"] = int(e.get("samples", 0)) + 1
                e["min_wire_bytes"] = min(int(e["min_wire_bytes"]), wire)
                e["max_wire_bytes"] = max(int(e["max_wire_bytes"]), wire)
            if tier:
                e["tier"] = tier

        for b in snapshot["buckets"]:
            fold(b.get("axes") or "data", int(b.get("wire_bytes", 0)),
                 float(b.get("wire_bytes_per_sec", 0.0)),
                 float(b.get("probe_secs", 0.0)))
        # hierarchical tier legs (probe hier_k) land under tiered keys
        # ("<axes>:intra" / "<axes>:inter") with an explicit tier field
        for t in snapshot.get("tiers") or []:
            tier = t.get("tier", "intra")
            fold(f"{t.get('axes') or 'data'}:{tier}",
                 int(t.get("wire_bytes", 0)),
                 float(t.get("wire_bytes_per_sec", 0.0)),
                 float(t.get("probe_secs", 0.0)), tier=tier)
        if not axes:
            return None
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        log.info("bandwidth catalog: folded %d bucket(s) into %s",
                 len(snapshot["buckets"]), path)
        return path
    except Exception:  # pragma: no cover - persistence is best effort
        log.exception("bandwidth catalog update failed (probe results "
                      "still live in comm_timing_stats)")
        return None


def list_catalogs() -> List[str]:
    """Every fabric catalog present (for ``main.py plan`` discovery)."""
    try:
        d = catalog_dir()
        return sorted(os.path.join(d, f) for f in os.listdir(d)
                      if f.endswith(".json"))
    except OSError:
        return []
