"""LR schedule tests — exact replay of the reference's recipes
(reference resnet_cifar_main.py:298-307, resnet_imagenet_main.py:236-247)."""
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.train.schedules import (
    create_schedule, piecewise, warmup_piecewise)
from distributed_resnet_tensorflow_tpu.utils.config import OptimizerConfig, get_preset


def test_cifar_piecewise_matches_reference():
    """0.1 until 40k, 0.01 until 60k, 0.001 until 80k, then 0.0001
    (reference resnet_cifar_main.py:298-307)."""
    s = piecewise((40000, 60000, 80000), (0.1, 0.01, 0.001, 0.0001))
    for step, want in [(0, 0.1), (39999, 0.1), (40000, 0.01), (59999, 0.01),
                       (60000, 0.001), (80000, 0.0001), (200000, 0.0001)]:
        assert np.isclose(float(s(step)), want), (step, float(s(step)))


def test_imagenet_warmup_piecewise_matches_reference():
    """Linear warmup 0.1→0.4 over 6240 steps, then ×0.1 drops
    (reference resnet_imagenet_main.py:236-247)."""
    s = warmup_piecewise(6240, 0.1, 0.4, (37440, 74880, 99840),
                         (0.4, 0.04, 0.004, 0.0004))
    assert np.isclose(float(s(0)), 0.1)
    assert np.isclose(float(s(3120)), 0.25, atol=1e-4)   # halfway
    assert np.isclose(float(s(6240)), 0.4)
    assert np.isclose(float(s(37439)), 0.4)
    assert np.isclose(float(s(37440)), 0.04)
    assert np.isclose(float(s(74880)), 0.004)
    assert np.isclose(float(s(99840)), 0.0004)


def test_piecewise_validation():
    with pytest.raises(ValueError):
        piecewise((10,), (0.1,))


def test_schedule_factory_from_presets():
    cifar = create_schedule(get_preset("cifar10_resnet50").optimizer)
    assert np.isclose(float(cifar(50000)), 0.01)
    imnet = create_schedule(get_preset("imagenet_resnet50").optimizer)
    assert np.isclose(float(imnet(6240)), 0.4)
    cos = create_schedule(OptimizerConfig(schedule="cosine", learning_rate=1.0,
                                          warmup_steps=10, total_steps=100))
    assert float(cos(10)) == pytest.approx(1.0, abs=1e-3)
    assert float(cos(100)) == pytest.approx(0.0, abs=1e-3)


def test_schedule_is_jittable():
    import jax
    s = create_schedule(get_preset("cifar10_resnet50").optimizer)
    f = jax.jit(s)
    assert np.isclose(float(f(jnp.asarray(45000))), 0.01)
