"""ImageNet pipeline tests: TFRecord codec, Example wire format, VGG
preprocessing, end-to-end iterator (reference resnet_imagenet_main.py:103-183
+ vgg_preprocessing.py behaviors)."""
import os

import numpy as np
import pytest

from distributed_resnet_tensorflow_tpu.data.tfrecord import (
    build_example, crc32c, masked_crc32c, parse_example, read_tfrecords,
    write_tfrecords)
from distributed_resnet_tensorflow_tpu.data.preprocessing import (
    RGB_MEANS, decode_jpeg, encode_jpeg, preprocess_for_eval,
    preprocess_for_train, _aspect_preserving_resize)
from distributed_resnet_tensorflow_tpu.data.imagenet import (
    dataset_filenames, imagenet_iterator)


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 zero bytes → 0x8A9136AA
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_tfrecord_roundtrip(tmp_path):
    path = str(tmp_path / "test.tfrecord")
    records = [b"hello", b"", b"x" * 1000]
    write_tfrecords(path, records)
    assert list(read_tfrecords(path, verify_crc=True)) == records


def test_tfrecord_corruption_detected(tmp_path):
    path = str(tmp_path / "bad.tfrecord")
    write_tfrecords(path, [b"payload-abc"])
    raw = bytearray(open(path, "rb").read())
    raw[14] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(bytes(raw))
    with pytest.raises(IOError):
        list(read_tfrecords(path, verify_crc=True))


# ---------------------------------------------------------------------------
# corrupt-record tolerance (data.max_corrupt_records)
# ---------------------------------------------------------------------------

def _fresh_stats():
    from distributed_resnet_tensorflow_tpu.data.tfrecord import (
        CorruptRecordStats)
    return CorruptRecordStats()


def test_tfrecord_bitrot_skipped_with_counted_warning(tmp_path):
    """Bit rot mid-shard: the damaged record is skipped (framing intact),
    every OTHER record still arrives, and the skip is tallied."""
    path = str(tmp_path / "rot.tfrecord")
    records = [b"alpha" * 10, b"bravo" * 10, b"charlie" * 10]
    write_tfrecords(path, records)
    raw = bytearray(open(path, "rb").read())
    # flip one byte inside the SECOND record's payload:
    # rec0 = 12B header + 50B payload + 4B crc = 66; rec1 payload at 66+12
    raw[66 + 12 + 5] ^= 0xFF
    open(path, "wb").write(bytes(raw))
    stats = _fresh_stats()
    out = list(read_tfrecords(path, verify_crc=True, max_corrupt=5,
                              stats=stats))
    assert out == [records[0], records[2]]
    snap = stats.snapshot()
    assert snap["count"] == 1
    assert snap["by_reason"] == {"corrupt data crc": 1}
    assert snap["recent"][0]["file"] == "rot.tfrecord"


def test_tfrecord_truncation_abandons_file_not_run(tmp_path):
    """A torn shard tail (half-written record) ends THAT file with a
    counted skip; strict mode still raises."""
    path = str(tmp_path / "torn.tfrecord")
    records = [b"one" * 20, b"two" * 20]
    write_tfrecords(path, records)
    size = len(open(path, "rb").read())
    with open(path, "r+b") as f:
        f.truncate(size - 30)  # tears the second record
    stats = _fresh_stats()
    out = list(read_tfrecords(path, max_corrupt=5, stats=stats))
    assert out == [records[0]]
    assert stats.snapshot()["by_reason"] == {"truncated record": 1}
    with pytest.raises(IOError, match="truncated"):
        list(read_tfrecords(path))  # max_corrupt=0: legacy strict behavior


def test_tfrecord_bitrot_undetected_without_verify_crc(tmp_path):
    """The documented tradeoff of the default verify_crc=False path:
    truncation is always caught, but flipped payload bytes pass through
    unflagged — catching those needs data.verify_crc=true (a python
    CRC32C pass per record)."""
    path = str(tmp_path / "rot.tfrecord")
    records = [b"alpha" * 10, b"bravo" * 10]
    write_tfrecords(path, records)
    raw = bytearray(open(path, "rb").read())
    raw[12 + 5] ^= 0xFF  # flip a byte inside record 0's payload
    open(path, "wb").write(bytes(raw))
    stats = _fresh_stats()
    out = list(read_tfrecords(path, max_corrupt=5, stats=stats))
    assert len(out) == 2 and out[0] != records[0]   # damage flows through
    assert stats.snapshot()["count"] == 0
    out = list(read_tfrecords(path, verify_crc=True, max_corrupt=5,
                              stats=stats))
    assert out == [records[1]]                       # caught with CRCs on
    assert stats.snapshot()["by_reason"] == {"corrupt data crc": 1}


def test_tfrecord_corrupt_budget_exhaustion_raises(tmp_path):
    """The tolerance is bounded: when the per-process tally exceeds
    max_corrupt, the reader raises — mass corruption is a storage
    incident, not noise."""
    stats = _fresh_stats()
    paths = []
    for i in range(3):
        p = str(tmp_path / f"rot{i}.tfrecord")
        write_tfrecords(p, [b"payload-abc"])
        raw = bytearray(open(p, "rb").read())
        raw[14] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        paths.append(p)
    list(read_tfrecords(paths[0], verify_crc=True, max_corrupt=2,
                        stats=stats))
    list(read_tfrecords(paths[1], verify_crc=True, max_corrupt=2,
                        stats=stats))
    with pytest.raises(IOError, match="max_corrupt_records"):
        list(read_tfrecords(paths[2], verify_crc=True, max_corrupt=2,
                            stats=stats))


def test_tfrecord_partial_trailing_header_is_eof_in_strict_mode(tmp_path):
    """The legacy reader treated 1-11 trailing bytes (torn mid-header) as
    silent EOF; strict mode (max_corrupt=0) must keep accepting files it
    always accepted. Tolerant mode counts the tear."""
    path = str(tmp_path / "tornhdr.tfrecord")
    records = [b"alpha" * 10]
    write_tfrecords(path, records)
    with open(path, "ab") as f:
        f.write(b"\x07\x00\x00")  # 3 bytes of a next record's header
    assert list(read_tfrecords(path)) == records          # strict: EOF
    stats = _fresh_stats()
    assert list(read_tfrecords(path, max_corrupt=5, stats=stats)) == records
    assert stats.snapshot()["by_reason"] == {"truncated header": 1}


def test_tfrecord_same_bad_record_across_epochs_costs_budget_once(tmp_path):
    """The input pipeline re-opens every shard each epoch: ONE unchanging
    bit-rotted record must consume the max_corrupt budget once, not once
    per pass — otherwise a multi-day run with a single bad record dies
    after max_corrupt epochs."""
    path = str(tmp_path / "rot.tfrecord")
    records = [b"alpha" * 10, b"bravo" * 10]
    write_tfrecords(path, records)
    raw = bytearray(open(path, "rb").read())
    raw[12 + 5] ^= 0xFF  # flip a byte inside record 0's payload
    open(path, "wb").write(bytes(raw))
    stats = _fresh_stats()
    for _epoch in range(5):  # 5 epochs >> max_corrupt=2
        out = list(read_tfrecords(path, verify_crc=True, max_corrupt=2,
                                  stats=stats))
        assert out == [records[1]]
    snap = stats.snapshot()
    assert snap["count"] == 1       # one distinct site, ever
    assert snap["repeats"] == 4     # later passes logged, not charged


def test_corrupt_records_hook_exports_event_rows(tmp_path):
    from distributed_resnet_tensorflow_tpu.data import tfrecord
    from distributed_resnet_tensorflow_tpu.train.hooks import (
        CorruptRecordsHook)
    from distributed_resnet_tensorflow_tpu.utils.metrics import (
        MetricsWriter, read_metrics)
    tfrecord.corrupt_records.reset()
    writer = MetricsWriter(str(tmp_path), enable_tensorboard=False)
    hook = CorruptRecordsHook(writer, every_steps=1)
    hook(1, None, {})  # nothing corrupt yet: no row
    tfrecord.corrupt_records.record("/data/train-007", "corrupt data crc")
    hook(2, None, {})
    hook(3, None, {})  # count unchanged: no duplicate row
    writer.close()
    rows = [r for r in read_metrics(str(tmp_path))
            if r.get("event") == "corrupt_record"]
    assert len(rows) == 1
    assert rows[0]["count"] == 1 and rows[0]["step"] == 2
    assert rows[0]["recent"][0]["file"] == "train-007"
    tfrecord.corrupt_records.reset()


def test_example_roundtrip():
    ex = build_example({
        "image/encoded": [b"\xff\xd8jpegdata"],
        "image/class/label": [42],
        "image/class/text": ["n01440764"],
        "image/object/bbox/xmin": [0.1, 0.5],
    })
    parsed = parse_example(ex)
    assert parsed["image/encoded"] == [b"\xff\xd8jpegdata"]
    assert parsed["image/class/label"] == [42]
    assert parsed["image/class/text"] == [b"n01440764"]
    assert np.allclose(parsed["image/object/bbox/xmin"], [0.1, 0.5], atol=1e-6)


@pytest.mark.heavy
def test_example_parse_real_tf_serialization():
    """Cross-check our wire parser against TensorFlow's own serializer."""
    tf = pytest.importorskip("tensorflow")
    ex = tf.train.Example(features=tf.train.Features(feature={
        "image/encoded": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[b"abc"])),
        "image/class/label": tf.train.Feature(
            int64_list=tf.train.Int64List(value=[7])),
        "f": tf.train.Feature(
            float_list=tf.train.FloatList(value=[1.5, -2.0])),
    }))
    parsed = parse_example(ex.SerializeToString())
    assert parsed["image/encoded"] == [b"abc"]
    assert parsed["image/class/label"] == [7]
    assert np.allclose(parsed["f"], [1.5, -2.0])


def test_jpeg_roundtrip():
    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (64, 48, 3), np.uint8)
    out = decode_jpeg(encode_jpeg(img, quality=95))
    assert out.shape == (64, 48, 3)
    assert abs(out.astype(int).mean() - img.astype(int).mean()) < 10


def test_aspect_preserving_resize():
    img = np.zeros((100, 200, 3), np.uint8)
    out = _aspect_preserving_resize(img, 50)
    assert out.shape == (50, 100, 3)
    out2 = _aspect_preserving_resize(np.zeros((200, 100, 3), np.uint8), 50)
    assert out2.shape == (100, 50, 3)


def test_preprocess_train_and_eval_shapes():
    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (300, 400, 3), np.uint8)
    tr = preprocess_for_train(img, rng, 224)
    assert tr.shape == (224, 224, 3) and tr.dtype == np.float32
    # mean-subtracted [0,1] range
    assert tr.min() >= -1.0 and tr.max() <= 1.0
    ev = preprocess_for_eval(img, 224)
    assert ev.shape == (224, 224, 3)
    # eval is deterministic
    np.testing.assert_array_equal(ev, preprocess_for_eval(img, 224))


def _write_fake_imagenet(tmp_path, shards=2, per_shard=6, size=64, mode="train"):
    rng = np.random.RandomState(0)
    prefix = "train" if mode == "train" else "validation"
    total = shards * per_shard
    for s in range(shards):
        recs = []
        for i in range(per_shard):
            img = rng.randint(0, 256, (size + 10 * s, size, 3), np.uint8)
            recs.append(build_example({
                "image/encoded": [encode_jpeg(img)],
                "image/class/label": [1 + (s * per_shard + i) % 1000],
            }))
        write_tfrecords(
            os.path.join(tmp_path, f"{prefix}-{s:05d}-of-{shards:05d}"), recs)
    return str(tmp_path), total


def test_imagenet_iterator_train(tmp_path):
    d, total = _write_fake_imagenet(tmp_path)
    it = imagenet_iterator(d, batch_size=4, mode="train", image_size=32,
                           num_decode_threads=2, shuffle_buffer=4)
    b = next(it)
    assert b["images"].shape == (4, 32, 32, 3)
    assert b["images"].dtype == np.float32
    assert b["labels"].dtype == np.int32
    assert (b["labels"] >= 1).all()


def test_imagenet_iterator_deterministic_across_builds(tmp_path):
    """Two identically-configured iterators in deterministic mode yield
    byte-identical batch streams despite 4 decode threads — the contract
    replica processes sharing a batch slice rely on (parallel/mesh.py
    process_batch_slice; main.py passes deterministic=True when the
    slice is replicated). Without the mode, workers emit in completion
    order and draw augmentation from per-worker RNG streams."""
    d, total = _write_fake_imagenet(tmp_path, shards=2, per_shard=8)

    def stream():
        it = imagenet_iterator(d, batch_size=4, mode="train", image_size=32,
                               num_decode_threads=4, shuffle_buffer=4,
                               deterministic=True)
        return [next(it) for _ in range(4)]

    a, b = stream(), stream()
    for ba, bb in zip(a, b):
        np.testing.assert_array_equal(ba["images"], bb["images"])
        np.testing.assert_array_equal(ba["labels"], bb["labels"])


def test_imagenet_eval_deterministic_and_complete(tmp_path):
    """Deterministic mode on the one-pass eval stream: identical batch
    order AND every record still delivered exactly once (the reorder
    buffer drains before the masked tail batch)."""
    d, total = _write_fake_imagenet(tmp_path, shards=2, per_shard=7,
                                    mode="validation")

    def labels():
        it = imagenet_iterator(d, batch_size=4, mode="eval", image_size=32,
                               num_decode_threads=4, deterministic=True)
        out, n = [], 0
        for b in it:
            mask = b.get("mask", np.ones(len(b["labels"])))
            out.append(b["labels"] * mask.astype(np.int32))
            n += int(mask.sum())
        return out, n

    la, na = labels()
    lb, nb = labels()
    assert na == nb == total
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


def test_imagenet_iterator_eval_exhausts_with_mask(tmp_path):
    d, total = _write_fake_imagenet(tmp_path, mode="validation")
    it = imagenet_iterator(d, batch_size=5, mode="eval", image_size=32,
                           num_decode_threads=2)
    batches = list(it)
    # 12 images in batches of 5 → 2 full + 1 masked partial
    counted = sum(int(b.get("mask", np.ones(5)).sum()) for b in batches)
    assert counted == total
    assert "mask" in batches[-1]


def test_imagenet_sharding_disjoint(tmp_path):
    d, total = _write_fake_imagenet(tmp_path, shards=4, per_shard=2,
                                    mode="validation")
    seen = []
    for idx in range(2):
        it = imagenet_iterator(d, batch_size=2, mode="eval", image_size=32,
                               shard_index=idx, num_shards=2,
                               num_decode_threads=1)
        for b in it:
            mask = b.get("mask", np.ones(len(b["labels"])))
            seen.extend(l for l, m in zip(b["labels"], mask) if m)
    assert len(seen) == total
    assert len(set(seen)) == total  # disjoint shards (Horovod-path fix)


def test_dataset_filenames_missing(tmp_path):
    with pytest.raises(FileNotFoundError):
        dataset_filenames(str(tmp_path), "train")


def test_decode_and_resize_matches_two_step():
    """Fused scaled decode == decode + aspect resize when no DCT scaling
    kicks in (upscale), and shape/range-correct when it does (downscale)."""
    from distributed_resnet_tensorflow_tpu.data.preprocessing import (
        _aspect_preserving_resize, decode_and_resize, decode_jpeg)
    rng = np.random.RandomState(7)
    img = rng.randint(0, 256, (96, 128, 3), np.uint8)
    data = encode_jpeg(img)
    up = decode_and_resize(data, 192)
    want = _aspect_preserving_resize(decode_jpeg(data), 192)
    np.testing.assert_array_equal(up, want)          # draft no-op on upscale
    # big source → the draft path actually engages (scale <= 1/2). Smooth
    # content: the two paths differ only in how they band-limit, so pure
    # pixel noise would decorrelate them while any real image agrees
    yy, xx = np.mgrid[0:512, 0:680].astype(np.float32)
    smooth = 128 + 60 * np.sin(yy / 40.0)[..., None] \
        + 50 * np.cos(xx / 55.0)[..., None] * np.array([1.0, 0.5, -0.5])
    big = np.clip(smooth + rng.normal(0, 8, (512, 680, 3)),
                  0, 255).astype(np.uint8)
    small = decode_and_resize(encode_jpeg(big), 128)
    assert small.shape[0] == 128 and small.dtype == np.uint8
    ref = _aspect_preserving_resize(decode_jpeg(encode_jpeg(big)), 128)
    assert small.shape == ref.shape
    # same image content modulo interpolation path: strong pixel correlation
    a = small.astype(np.float32).ravel()
    b = ref.astype(np.float32).ravel()
    corr = np.corrcoef(a, b)[0, 1]
    assert corr > 0.9, corr


def test_imagenet_iterator_uint8_device_standardize(tmp_path):
    """device_standardize=True train batches are raw uint8 crops; applying
    the device vgg_standardize reproduces the host float path's range."""
    d, total = _write_fake_imagenet(tmp_path)
    it = imagenet_iterator(d, batch_size=4, mode="train", image_size=32,
                           num_decode_threads=2, shuffle_buffer=4,
                           device_standardize=True)
    b = next(it)
    assert b["images"].dtype == np.uint8
    from distributed_resnet_tensorflow_tpu.ops.augment import vgg_standardize
    out = np.asarray(vgg_standardize(b["images"], None))
    assert out.dtype == np.float32
    assert out.min() >= -1.0 and out.max() <= 1.0
    # exact parity with the host-side standardization formula
    from distributed_resnet_tensorflow_tpu.data.preprocessing import RGB_MEANS
    want = b["images"].astype(np.float32) / 255.0 - RGB_MEANS
    np.testing.assert_allclose(out, want, atol=1e-6)
    # r4: eval ships uint8 too (make_eval_step applies the deterministic
    # standardize on device — see test_eval_uint8_metrics_match below)
    _write_fake_imagenet(tmp_path, mode="validation")
    it_ev = imagenet_iterator(d, batch_size=4, mode="eval", image_size=32,
                              device_standardize=True)
    assert next(it_ev)["images"].dtype == np.uint8


@pytest.mark.heavy
def test_eval_uint8_metrics_match(tmp_path):
    """A full eval pass over the uint8 (device-standardize) iterator with
    the prep-hooked eval step == the host-float pass bit-for-bit on
    correctness counts (same images, same masked tail)."""
    from distributed_resnet_tensorflow_tpu.train.loop import make_eval_step
    from distributed_resnet_tensorflow_tpu.train.state import (
        create_train_state)
    from distributed_resnet_tensorflow_tpu.models import CifarResNetV2
    import jax
    import jax.numpy as jnp
    import optax
    from distributed_resnet_tensorflow_tpu.ops.augment import vgg_standardize

    d, total = _write_fake_imagenet(tmp_path, mode="validation")
    model = CifarResNetV2(resnet_size=8, num_classes=8, dtype=jnp.float32)
    state = create_train_state(jax.random.PRNGKey(0), model,
                               optax.sgd(0.1), (1, 32, 32, 3))

    def run(device_standardize):
        it = imagenet_iterator(d, batch_size=5, mode="eval", image_size=32,
                               device_standardize=device_standardize)
        step = make_eval_step(vgg_standardize if device_standardize else None)
        totals = None
        for b in it:
            out = step(state, {k: jnp.asarray(v) for k, v in b.items()})
            totals = out if totals is None else jax.tree_util.tree_map(
                jnp.add, totals, out)
        return totals

    host = run(False)
    dev = run(True)
    assert int(host["count"]) == total and int(dev["count"]) == total
    assert int(host["correct"]) == int(dev["correct"])
    np.testing.assert_allclose(float(host["loss_sum"]),
                               float(dev["loss_sum"]), rtol=1e-5)


def test_decode_processes_pool(tmp_path):
    """decode_processes > 0: the fork-based worker pool yields the same
    record multiset as the thread pool (eval mode — deterministic set),
    exhausts cleanly, and propagates the masked tail."""
    d, total = _write_fake_imagenet(tmp_path, mode="validation")
    it = imagenet_iterator(d, batch_size=5, mode="eval", image_size=32,
                           decode_processes=2)
    labels = []
    got_mask = False
    for b in it:
        mask = b.get("mask", np.ones(len(b["labels"])))
        got_mask = got_mask or "mask" in b
        labels.extend(int(l) for l, m in zip(b["labels"], mask) if m)
    assert len(labels) == total
    assert got_mask
    it2 = imagenet_iterator(d, batch_size=5, mode="eval", image_size=32,
                            num_decode_threads=2)
    ref = []
    for b in it2:
        mask = b.get("mask", np.ones(len(b["labels"])))
        ref.extend(int(l) for l, m in zip(b["labels"], mask) if m)
    assert sorted(labels) == sorted(ref)
