"""Device prefetch — overlap host→device transfer with device compute.

The reference's analog was tf.data's prefetch-to-device buffering
(prefetch(2*bs), reference resnet_cifar_main.py:232). Here: wrap a host batch
iterator so batch i+1's ``device_put`` is dispatched while the jitted step for
batch i is still running — JAX transfers are asynchronous, so keeping one
batch in flight hides the PCIe/DCN copy entirely when compute per step
exceeds transfer time.
"""
from __future__ import annotations

import collections
from typing import Callable, Iterator


def device_prefetch(host_iter: Iterator, put: Callable, depth: int = 2
                    ) -> Iterator:
    """Yield device-resident batches with ``depth`` transfers in flight.

    ``put`` is the host→device placement fn (e.g. Trainer._put_batch). The
    queue keeps ``depth`` batches already dispatched; pulling one immediately
    dispatches the next, so transfers run behind compute.
    """
    queue: collections.deque = collections.deque()
    try:
        for _ in range(depth):
            queue.append(put(next(host_iter)))
    except StopIteration:
        pass
    while queue:
        out = queue.popleft()
        try:
            queue.append(put(next(host_iter)))
        except StopIteration:
            pass
        yield out
