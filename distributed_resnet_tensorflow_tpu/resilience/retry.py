"""Bounded retry with exponential backoff + jitter.

The reference had no retry anywhere: a flaky NFS stat during a checkpoint
save or a coordinator that came up a second late killed the whole SLURM job
(SURVEY.md §4.4 — failure handling was "SLURM restarts everything"). At the
scales this framework targets (hundreds of hosts, "Massively Distributed
SGD" arXiv:1811.05233) transient faults are the common case, so the I/O and
bootstrap edges — distributed init (parallel/distributed.py), checkpoint
reads/writes (checkpoint/manager.py), native-loader opens
(data/native_loader.py) — route through this one bounded helper instead of
each growing an ad-hoc sleep loop.

Deliberately dependency-free and cheap to import.
"""
from __future__ import annotations

import logging
import random
import time
from typing import Callable, Optional, Tuple, Type

log = logging.getLogger(__name__)


def retry_call(fn: Callable, *args,
               retries: int = 3,
               base_delay: float = 0.2,
               max_delay: float = 5.0,
               jitter: float = 0.5,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               giveup: Optional[Callable[[BaseException], bool]] = None,
               description: str = "",
               sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on a ``retry_on`` exception, back off
    exponentially (``base_delay * 2**attempt``, capped at ``max_delay``,
    ±``jitter`` fraction randomized so a fleet of hosts doesn't retry in
    lockstep) and try again, at most ``retries`` extra times.

    ``giveup(exc) -> True`` marks an exception permanent (re-raised
    immediately without burning retries) — e.g. "already initialized" from
    ``jax.distributed``. The final failure re-raises the original exception
    unchanged so callers' except clauses keep working.
    """
    if retries < 0:
        raise ValueError(f"retries must be >= 0, got {retries}")
    what = description or getattr(fn, "__name__", repr(fn))
    for attempt in range(retries + 1):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            if giveup is not None and giveup(e):
                raise
            if attempt >= retries:
                log.warning("%s failed after %d attempt(s): %s",
                            what, retries + 1, e)
                raise
            delay = min(max_delay, base_delay * (2 ** attempt))
            delay *= 1.0 + jitter * (2.0 * random.random() - 1.0)
            delay = max(0.0, delay)
            log.warning("%s failed (attempt %d/%d): %s — retrying in %.2fs",
                        what, attempt + 1, retries + 1, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover


