"""ImageNet input pipeline over TFRecord shards.

Parity with the reference's duplicated input_fn/record_parser
(reference resnet_imagenet_main.py:103-183, resnet_imagenet_eval.py:70-150):
  * shard naming train-{i:05d}-of-01024 / validation-{i:05d}-of-00128
    (reference :106-112),
  * Example parsing of image/encoded + image/class/label
    (reference record_parser:115-136; bbox features parsed but unused by the
    crop the reference actually applied — VGG preprocessing ignores them),
  * file-level shuffle each epoch + sample-level shuffle buffer
    (reference :98-99,163,174),
  * VGG preprocess train/eval (preprocessing.py), labels already 1-based
    with 0 = background ⇒ num_classes=1001 dense ids (the reference one-hotted
    to 1001, resnet_imagenet_main.py:151-155; we keep dense ids and one-hot
    in the loss).

Multi-process sharding: each process reads files[shard_index::num_shards] —
disjoint by construction (the reference's Horovod path read everything
everywhere, SURVEY.md §3.2).

Parallelism: a pool of decode threads feeding a bounded queue — host-side
successor of tf.data's num_parallel_calls=5 map (reference :166-168). Each
worker decodes via PIL (DCT-scaled draft) or, with ``use_native`` and a
libjpeg-enabled build, the fused C++ transform (native/dataloader.cc —
scaled decode + resize/crop/flip in one GIL-free call, measured 1.6× the
PIL rate per core); the C++ record prefetcher feeds the bytes.
"""
from __future__ import annotations

import glob
import os
import queue as queue_mod
import threading
from typing import Dict, Iterator, List, Optional

import numpy as np

from .tfrecord import parse_example, read_tfrecords

TRAIN_SHARDS = 1024   # reference resnet_imagenet_main.py:106
VAL_SHARDS = 128      # reference resnet_imagenet_main.py:111
SHUFFLE_BUFFER = 1500  # reference resnet_imagenet_main.py:174


def dataset_filenames(data_dir: str, mode: str) -> List[str]:
    """Accept both the exact reference naming and any train-*/validation-*
    TFRecord layout present in data_dir."""
    prefix = "train" if mode == "train" else "validation"
    files = sorted(glob.glob(os.path.join(data_dir, f"{prefix}-*")))
    if not files:
        raise FileNotFoundError(
            f"no {prefix}-* TFRecord shards under {data_dir!r}")
    return files


def _example_to_sample(features: Dict) -> Optional[tuple]:
    enc = features.get("image/encoded")
    label = features.get("image/class/label")
    if not enc or label is None or len(label) == 0:
        return None
    return bytes(enc[0]), int(label[0])


def imagenet_iterator(data_dir: str, batch_size: int, mode: str,
                      image_size: int = 224, seed: int = 0,
                      shard_index: int = 0, num_shards: int = 1,
                      num_decode_threads: int = 4,
                      prefetch_batches: int = 2,
                      shuffle_buffer: int = SHUFFLE_BUFFER,
                      use_native: bool = False,
                      device_standardize: bool = False,
                      decode_processes: int = 0,
                      ) -> Iterator[Dict[str, np.ndarray]]:
    """``device_standardize``: batches stay uint8 (crop/flip done, VGG
    mean-subtract deferred to ops/augment.vgg_standardize inside the jitted
    step) — 4× smaller host→device transfers and no host float pass. Both
    modes use the fused DCT-scaled decode (preprocessing.decode_and_resize).

    ``decode_processes`` > 0 replaces the decode THREAD pool with worker
    PROCESSES (fork): full GIL independence for the decode stage, at the
    price of pickling jpeg bytes in and decoded crops out. The thread pool
    already scales while decoders hold the GIL released (PIL and the
    native transform both release it); the process pool is the escape
    hatch for hosts where the python-side feeder contends
    (tools/input_scaling.py measures both, docs/input_scaling_r4.json).
    Workers start via forkserver/spawn (fork from a threaded parent can
    inherit held locks), so the calling program needs the standard
    ``if __name__ == "__main__"`` guard multiprocessing requires.
    """
    files = dataset_filenames(data_dir, mode)
    if num_shards > 1:
        total_files = len(files)
        files = files[shard_index::num_shards]
        if not files:
            raise ValueError(f"process {shard_index}: no files to read "
                             f"({num_shards} shards over {total_files} files)")
    is_train = mode == "train"
    rng = np.random.RandomState(seed + shard_index)

    # native C++ multithreaded record reader. Train: file order is
    # thread-interleaved → extra shuffle for free. Eval (round 4): also
    # allowed — aggregate eval metrics are order-independent and the
    # prefetcher delivers every record exactly once, so only the
    # meaningless per-batch composition changes (VERDICT r3 #6: the
    # single-stream python reader capped a 50k validation pass)
    native = use_native
    if native:
        try:
            from .native_loader import NativePrefetcher, native_available
            native = native_available()
        except Exception:
            native = False

    def record_stream(ordered_files):
        if native:
            pf = NativePrefetcher(list(ordered_files),
                                  num_threads=min(4, len(ordered_files)))
            try:
                yield from pf
            finally:
                pf.close()
        else:
            for path in ordered_files:
                yield from read_tfrecords(path)

    # stage 1: raw (jpeg_bytes, label) stream with file + buffer shuffle
    def raw_stream():
        epoch = 0
        while True:
            order = rng.permutation(len(files)) if is_train else range(len(files))
            buf: List[tuple] = []
            for rec in record_stream([files[fi] for fi in order]):
                sample = _example_to_sample(parse_example(rec))
                if sample is None:
                    continue
                if is_train and shuffle_buffer > 1:
                    buf.append(sample)
                    if len(buf) >= shuffle_buffer:
                        j = rng.randint(len(buf))
                        yield buf.pop(j)
                else:
                    yield sample
            while buf:
                j = rng.randint(len(buf))
                yield buf.pop(j)
            epoch += 1
            if not is_train:
                return

    # stage 2: parallel decode+preprocess workers (threads, or processes
    # when decode_processes > 0)
    use_procs = decode_processes > 0
    n_workers = decode_processes if use_procs else num_decode_threads
    emit_uint8 = device_standardize
    # the fused C++ decode (one GIL-free call per image) when built with
    # libjpeg; PIL otherwise — identical crop geometry either way
    native_decode = False
    if use_native:
        try:
            from .native_loader import native_jpeg_available
            native_decode = native_jpeg_available()
        except Exception:
            native_decode = False

    if use_procs:
        import multiprocessing as mp
        # NOT "fork": the parent is multi-threaded by the time an iterator
        # is built (JAX runtime threads, earlier iterators' feeders), and a
        # child forked while another thread holds a lock (malloc, logging)
        # can deadlock — observed nondeterministically in round 4.
        # forkserver forks from a clean single-threaded server process;
        # spawn is the fallback where it's unavailable. The worker body
        # (_decode_worker) is module-level and numpy/PIL-only, so both
        # start methods can import it.
        try:
            ctx = mp.get_context("forkserver")
        except ValueError:  # platform without forkserver
            ctx = mp.get_context("spawn")
        in_q = ctx.Queue(maxsize=4 * batch_size)
        out_q = ctx.Queue(maxsize=max(2, prefetch_batches) * batch_size)
        workers = [
            ctx.Process(target=_decode_worker,
                        args=(in_q, out_q, seed * 7919 + i, is_train,
                              image_size, native_decode, emit_uint8),
                        daemon=True)
            for i in range(n_workers)]
        for w in workers:
            w.start()
        # parent only, AFTER the workers start (children must keep normal
        # join semantics so their final puts flush at exit): without this,
        # an abandoned iterator leaves the parent's atexit joining a queue
        # feeder thread that can never drain once workers are gone
        in_q.cancel_join_thread()
        out_q.cancel_join_thread()
    else:
        in_q = queue_mod.Queue(maxsize=4 * batch_size)
        out_q = queue_mod.Queue(
            maxsize=max(2, prefetch_batches) * batch_size)
    stop = threading.Event()

    def _put_checked(item) -> bool:
        """Timed put so the feeder notices `stop` even when the queue is
        full (a blocking put would never wake once consumers are gone —
        at interpreter exit multiprocessing joins its queue threads and a
        stuck feeder turns teardown into a hang)."""
        while not stop.is_set():
            try:
                in_q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def feeder():
        try:
            for sample in raw_stream():
                if not _put_checked(sample):
                    return
            for _ in range(n_workers):
                if not _put_checked(_END):
                    return
        except BaseException as e:
            out_q.put(_Failure(repr(e)))

    def decoder(widx: int):
        try:
            _decode_loop(in_q, out_q, seed * 7919 + widx, is_train,
                         image_size, native_decode, emit_uint8, stop)
        except BaseException as e:
            out_q.put(_Failure(repr(e)))

    threading.Thread(target=feeder, daemon=True).start()
    if not use_procs:
        for i in range(n_workers):
            threading.Thread(target=decoder, args=(i,), daemon=True).start()

    def batches():
        images = np.empty((batch_size, image_size, image_size, 3),
                          np.uint8 if emit_uint8 else np.float32)
        labels = np.empty((batch_size,), np.int32)
        fill = 0
        ended = 0

        def next_item():
            if not use_procs:
                return out_q.get()
            # a worker killed by a signal (segfault, OOM killer) enqueues
            # neither _Failure nor _END — poll liveness so that becomes a
            # loud error instead of a permanent out_q.get() block
            while True:
                try:
                    return out_q.get(timeout=5.0)
                except queue_mod.Empty:
                    dead = [w for w in workers if not w.is_alive()
                            and w.exitcode not in (0, None)]
                    if dead:
                        raise RuntimeError(
                            "imagenet decode worker(s) died without "
                            f"reporting: exitcodes "
                            f"{[w.exitcode for w in dead]}") from None

        try:
            while True:
                item = next_item()
                if isinstance(item, _Failure):
                    raise RuntimeError(
                        f"imagenet pipeline worker failed: {item.err}")
                if item is _END or isinstance(item, _EndMarker):
                    ended += 1
                    if ended == n_workers:
                        if fill and not is_train:
                            # final partial eval batch: pad + mask
                            mask = np.zeros((batch_size,), np.float32)
                            mask[:fill] = 1.0
                            images[fill:] = 0.0
                            labels[fill:] = 0
                            yield {"images": images.copy(),
                                   "labels": labels.copy(), "mask": mask}
                        return
                    continue
                images[fill], labels[fill] = item
                fill += 1
                if fill == batch_size:
                    yield {"images": images.copy(), "labels": labels.copy()}
                    fill = 0
        finally:
            stop.set()
            if use_procs:
                # don't let atexit try to flush/join the queue threads:
                # with the workers gone the pipes never drain
                in_q.cancel_join_thread()
                out_q.cancel_join_thread()
                for w in workers:
                    w.terminate()

    return batches()


class _EndMarker:
    """Worker-exhausted sentinel that survives a multiprocessing queue."""


class _Failure:
    def __init__(self, err: str):
        self.err = err


_END = _EndMarker()


def _decode_loop(in_q, out_q, wseed, is_train, image_size, native_decode,
                 emit_uint8, stop=None):
    from .preprocessing import (RGB_MEANS, eval_crop_from_bytes,
                                train_crop_from_bytes)
    import queue as queue_mod
    wrng = np.random.RandomState(wseed)

    def put_checked(item) -> bool:
        """Timed put in thread mode so `stop` is observed even on a FULL
        out_q (decoders outpacing an abandoned consumer park here, not in
        get). Process mode (stop=None) keeps the blocking put — workers
        are terminate()d."""
        if stop is None:
            out_q.put(item)
            return True
        while not stop.is_set():
            try:
                out_q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    while stop is None or not stop.is_set():
        # timed get in thread mode so `stop` is observed between items: an
        # abandoned iterator (eval warmup, a polling evaluator sized below
        # the dataset) sets `stop` while workers sit in get(); a blocking
        # get would strand num_decode_threads daemon threads per iterator,
        # growing unboundedly in a long-lived poll loop.
        try:
            item = in_q.get(timeout=None if stop is None else 0.2)
        except queue_mod.Empty:
            continue
        if item is _END or isinstance(item, _EndMarker):
            put_checked(_END)
            return
        data, label = item
        if is_train:
            img = train_crop_from_bytes(data, wrng, image_size,
                                        use_native=native_decode)
        else:
            img = eval_crop_from_bytes(data, image_size,
                                       use_native=native_decode)
        if not emit_uint8:
            img = img.astype(np.float32) / 255.0 - RGB_MEANS
        if not put_checked((img, label)):
            return


def _decode_worker(in_q, out_q, wseed, is_train, image_size, native_decode,
                   emit_uint8):
    """Process-pool worker body (fork target)."""
    try:
        _decode_loop(in_q, out_q, wseed, is_train, image_size,
                     native_decode, emit_uint8)
    except BaseException as e:  # pragma: no cover - transported to parent
        out_q.put(_Failure(repr(e)))
