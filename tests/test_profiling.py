"""Profiling subsystem tests (SURVEY.md §5 tracing/profiling parity)."""
import jax
import numpy as np

from distributed_resnet_tensorflow_tpu.utils import profiling


def test_count_params():
    tree = {"a": np.zeros((3, 4)), "b": {"c": np.zeros(5)}}
    assert profiling.count_params(tree) == 17


def test_flops_and_mfu():
    @jax.jit
    def f(x):
        return x @ x

    x = np.zeros((256, 256), np.float32)
    flops = profiling.flops_per_step(f, x)
    assert flops is None or flops >= 2 * 256**3 * 0.5  # matmul-dominated
    # mfu with explicit peak
    out = profiling.mfu(steps_per_sec=100.0, step_flops=1e9,
                        num_devices=1, peak_tflops=100.0)
    assert np.isclose(out, 1e11 / 1e14)


def test_trace_writes_profile(tmp_path):
    with profiling.trace(str(tmp_path)):
        jax.jit(lambda x: x + 1)(np.zeros(4, np.float32)).block_until_ready()
    import os
    found = any("plugins" in root or f.endswith(".pb") or "trace" in f.lower()
                for root, _, fs in os.walk(tmp_path) for f in fs)
    assert found
