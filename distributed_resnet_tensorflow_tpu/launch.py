"""Multi-process launcher — successor of the reference's launcher tree.

The reference bootstrapped clusters with ~440 lines of bash deriving ps/worker
host:port lists from SLURM and synthesizing per-node scripts
(reference scripts/run_dist_tf_daint.sh:30-206, SURVEY.md §2.18). In the SPMD
world a launcher only needs to start N identical processes with
(coordinator, process_id) — everything else is the same program.

Modes:
  * ``--num_processes N`` local fan-out — the successor of the reference's
    1ps+2wk localhost smoke cluster (reference scripts/submit_mac_dist.sh,
    run_dist_tf_local.sh: bs=10, 100 steps on CPU). Each child gets a fake
    single-CPU-device platform unless --devices_per_process says otherwise.
  * under SLURM, don't use this at all: ``srun python -m
    distributed_resnet_tensorflow_tpu.main …`` — parallel/distributed.py
    reads SLURM_NTASKS/SLURM_PROCID/nodelist itself (scripts/submit_tpu_slurm.sh).
  * on Cloud TPU pods, run main.py on every TPU VM worker;
    jax.distributed.initialize autodetects the pod topology (no args needed).

Usage:
    python -m distributed_resnet_tensorflow_tpu.launch --num_processes 2 -- \
        --preset smoke --set train.train_steps=20
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import subprocess
import sys
from typing import List

from distributed_resnet_tensorflow_tpu.resilience.preemption import (
    RESUMABLE_EXIT_CODE)

log = logging.getLogger(__name__)


def launch_local(num_processes: int, main_args: List[str],
                 devices_per_process: int = 0, port: int = 8476) -> int:
    """Spawn N copies of main.py on localhost over the loopback coordinator.
    Returns the first nonzero exit code (0 if all succeed).

    ``devices_per_process=0`` (default) honors a device count the user
    already exported via XLA_FLAGS, falling back to 1."""
    from distributed_resnet_tensorflow_tpu.utils.virtual_devices import (
        existing_device_count, virtual_cpu_env)

    if not devices_per_process:
        devices_per_process = existing_device_count(
            os.environ.get("XLA_FLAGS", "")) or 1
    procs = []
    for pid in range(num_processes):
        env = virtual_cpu_env(devices_per_process)
        cmd = [sys.executable, "-m", "distributed_resnet_tensorflow_tpu.main",
               *main_args,
               "--set", f"mesh.coordinator_address=127.0.0.1:{port}",
               "--set", f"mesh.num_processes={num_processes}",
               "--set", f"mesh.process_id={pid}"]
        # chief inherits stdout/stderr; others keep their own log files —
        # per-process logs like the reference's worker.$JOBID.$host.log
        # (reference run_dist_train_eval_daint.sh:161,188)
        if pid == 0:
            out = None
        else:
            os.makedirs("/tmp/drt_launch", exist_ok=True)
            out = open(f"/tmp/drt_launch/proc{pid}.log", "w")
        procs.append(subprocess.Popen(cmd, env=env, stdout=out, stderr=out))

    # forward SIGTERM (SLURM grace-period kill, kill.sh) to every child so
    # each commits its preemption checkpoint and exits resumable; the
    # launcher then reports the children's own exit code
    def forward_term(signum, frame):
        for p in procs:
            try:
                p.send_signal(signal.SIGTERM)
            except ProcessLookupError:
                pass

    try:
        prev_term = signal.signal(signal.SIGTERM, forward_term)
    except ValueError:  # not the main thread (embedded use) — no forwarding
        prev_term = None
    rc = 0
    try:
        for p in procs:
            code = p.wait()
            # precedence: real failure > resumable (75) > clean, regardless
            # of child reap order — a genuinely failing job must never be
            # masked as merely preempted (the SLURM shim would requeue it)
            if code != 0 and rc in (0, RESUMABLE_EXIT_CODE):
                rc = code
    except KeyboardInterrupt:  # kill.sh parity (reference scripts/kill.sh)
        for p in procs:
            p.send_signal(signal.SIGTERM)
        rc = 130
    finally:
        if prev_term is not None:
            signal.signal(signal.SIGTERM, prev_term)
    if rc == RESUMABLE_EXIT_CODE:
        log.warning("children preempted; exit code %d marks the run "
                    "resumable — relaunch with the same config to resume",
                    RESUMABLE_EXIT_CODE)
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="local multi-process SPMD launcher")
    ap.add_argument("--num_processes", type=int, default=2)
    ap.add_argument("--devices_per_process", type=int, default=0,
                    help="0 = inherit XLA_FLAGS device count, else 1")
    ap.add_argument("--port", type=int, default=8476)
    ap.add_argument("main_args", nargs=argparse.REMAINDER,
                    help="args after -- go to main.py")
    ns = ap.parse_args(argv)
    main_args = ns.main_args
    if main_args and main_args[0] == "--":
        main_args = main_args[1:]
    sys.exit(launch_local(ns.num_processes, main_args,
                          ns.devices_per_process, ns.port))


if __name__ == "__main__":
    main()
