"""Virtual CPU device-mesh env setup, shared by every fake-mesh entry point.

The JAX analog of the reference's "local smoke cluster" trick (reference
scripts/submit_mac_dist.sh:9-39 — 1ps+2wk on localhost CPU): N virtual host
devices via ``--xla_force_host_platform_device_count`` so sharding and
collective paths run without real accelerators. Used by the test conftest,
the local multi-process launcher, and the driver's multi-chip dry run.

Deliberately imports nothing heavy (no jax) — callers set the environment
*before* the JAX backend initializes. NOTE: this environment's
sitecustomize overrides the JAX_PLATFORMS env var via jax.config at
interpreter startup, so in-process callers must additionally run
``jax.config.update("jax_platforms", "cpu")`` before first backend use;
subprocess callers must have the child do so.
"""
from __future__ import annotations

import os
from typing import Mapping, MutableMapping, Optional


def virtual_cpu_flags(n_devices: int, existing: str = "") -> str:
    """XLA_FLAGS value forcing ``n_devices`` virtual host devices, replacing
    (not merely appending to) any existing device-count flag so a stale
    smaller count can't win."""
    flags = [f for f in existing.split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    return " ".join(flags)


def existing_device_count(xla_flags: str) -> int:
    """Device count from an existing --xla_force_host_platform_device_count
    flag, or 0 when absent/malformed."""
    for f in xla_flags.split():
        if "xla_force_host_platform_device_count" in f and "=" in f:
            try:
                return int(f.split("=", 1)[1])
            except ValueError:
                return 0
    return 0


def virtual_cpu_env(n_devices: int,
                    base: Optional[Mapping[str, str]] = None) -> dict:
    """A copy of ``base`` (default ``os.environ``) set up for an
    ``n_devices``-device virtual CPU platform — for subprocess launches."""
    env = dict(os.environ if base is None else base)
    env["XLA_FLAGS"] = virtual_cpu_flags(n_devices, env.get("XLA_FLAGS", ""))
    env["JAX_PLATFORMS"] = "cpu"
    return env


def apply_virtual_cpu(n_devices: int,
                      env: Optional[MutableMapping[str, str]] = None) -> None:
    """In-place variant for the current process: set XLA_FLAGS and force the
    CPU platform. Call before the JAX backend initializes."""
    target = os.environ if env is None else env
    target["XLA_FLAGS"] = virtual_cpu_flags(
        n_devices, target.get("XLA_FLAGS", ""))
    force_cpu_platform()


def force_cpu_platform() -> None:
    """Flip the platform to CPU through jax.config — required because the
    sitecustomize override beats the JAX_PLATFORMS env var. Lazy jax import
    so merely importing this module stays lightweight."""
    import jax

    jax.config.update("jax_platforms", "cpu")
