"""VGG-style ImageNet preprocessing — numpy/PIL re-expression.

Parity with reference vgg_preprocessing.py:
  * train: resize shorter side to a random scale in [256, 512]
    (reference :284-314), random 224x224 crop (reference _random_crop:88),
    random horizontal flip, RGB mean subtraction with means scaled to the
    [0,1] pixel range (reference :37-39: _R_MEAN=123.68/255 etc.)
  * eval: resize shorter side to 256, central 224x224 crop
    (reference preprocess_for_eval:317-333)

Decoding + resizing happen on the host (PIL), the cheap float ops in numpy;
the TPU sees ready, fixed-shape float32 NHWC batches.
"""
from __future__ import annotations

import io
from typing import Optional, Tuple

import numpy as np

# reference vgg_preprocessing.py:37-39 (means already divided by 255)
R_MEAN = 123.68 / 255.0
G_MEAN = 116.78 / 255.0
B_MEAN = 103.94 / 255.0
RGB_MEANS = np.asarray([R_MEAN, G_MEAN, B_MEAN], np.float32)

RESIZE_SIDE_MIN = 256   # reference vgg_preprocessing.py:41-42
RESIZE_SIDE_MAX = 512
DEFAULT_IMAGE_SIZE = 224


def decode_jpeg(data: bytes) -> np.ndarray:
    """JPEG/PNG bytes → RGB uint8 HWC."""
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return np.asarray(img, np.uint8)


def decode_and_resize(data: bytes, smaller_side: int) -> np.ndarray:
    """JPEG bytes → RGB uint8 resized so min(h,w) == smaller_side.

    Fuses the decode with the aspect-preserving resize and exploits
    libjpeg's DCT-domain scaled decode (PIL ``draft``): when the target is
    ≤ 1/2 the source, the decoder emits 1/2, 1/4 or 1/8-scale pixels
    directly — decoding a fraction of the blocks — and one bilinear resize
    lands the exact size. 2-3× faster than full decode + resize on typical
    ImageNet sources, with only the interpolation path differing from
    decode_jpeg + _aspect_preserving_resize (DCT box-downscale feeding the
    bilinear instead of full-res pixels)."""
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    w, h = img.size
    scale = smaller_side / min(w, h)
    tw, th = max(1, round(w * scale)), max(1, round(h * scale))
    img.draft("RGB", (tw, th))  # no-op for non-JPEG or upscales
    if img.mode != "RGB":
        img = img.convert("RGB")
    if img.size != (tw, th):
        img = img.resize((tw, th), Image.BILINEAR)
    return np.asarray(img, np.uint8)


def _random_crop_flip(image: np.ndarray, rng: np.random.RandomState,
                      output_size: int,
                      apply_flip: bool = True) -> np.ndarray:
    """Random output_size² crop + horizontal flip (reference
    _random_crop:88 + flip). One definition shared by the decoded-array and
    the fused-decode paths — the RNG draw order (top, left, flip) is part
    of the contract: with ``apply_flip=False`` (device-side augmentation
    owns the flip — ops/augment.imagenet_train_augment) the flip is still
    DRAWN, just not applied, so a fixed seed selects identical crop
    geometry whichever side flips."""
    h, w = image.shape[:2]
    top = rng.randint(0, h - output_size + 1)
    left = rng.randint(0, w - output_size + 1)
    crop = image[top:top + output_size, left:left + output_size]
    if rng.rand() < 0.5 and apply_flip:
        crop = crop[:, ::-1]
    return crop


def _center_crop(image: np.ndarray, output_size: int) -> np.ndarray:
    """Central crop (reference _central_crop:171)."""
    h, w = image.shape[:2]
    top = (h - output_size) // 2
    left = (w - output_size) // 2
    return image[top:top + output_size, left:left + output_size]


def _header_dims(data: bytes):
    """(w, h) from the image header only — no pixel decode."""
    from PIL import Image
    return Image.open(io.BytesIO(data)).size


def _resized_dims(w0: int, h0: int, side: int):
    scale = side / min(w0, h0)
    return max(1, round(w0 * scale)), max(1, round(h0 * scale))


def train_crop_from_bytes(data: bytes, rng: np.random.RandomState,
                          output_size: int = DEFAULT_IMAGE_SIZE,
                          resize_side_min: int = RESIZE_SIDE_MIN,
                          resize_side_max: int = RESIZE_SIDE_MAX,
                          use_native: bool = False,
                          apply_flip: bool = True) -> np.ndarray:
    """VGG train preprocessing, uint8 end-to-end (standardization is the
    device's job — ops/augment.vgg_standardize): random resize side via a
    fused scaled decode, random crop, random flip.

    ``use_native`` routes the decode+resize+crop+flip through ONE C++ call
    (native_loader.decode_resize_crop_native — DCT-scaled libjpeg decode
    sampling only the crop window; the ctypes call releases the GIL). The
    RNG draw order (side, top, left, flip) and the resized-dims arithmetic
    are identical on both paths, so a fixed seed selects the same crop
    geometry either way; pixels differ only by the interpolation path."""
    side = rng.randint(resize_side_min, resize_side_max + 1)
    if use_native:
        try:
            w0, h0 = _header_dims(data)
        except Exception:
            w0 = None
        if w0:
            rw, rh = _resized_dims(w0, h0, side)
            top = rng.randint(0, max(1, rh - output_size + 1))
            left = rng.randint(0, max(1, rw - output_size + 1))
            flip = bool(rng.rand() < 0.5) and apply_flip
            from .native_loader import decode_resize_crop_native
            out = decode_resize_crop_native(data, side, top, left,
                                            output_size, flip)
            if out is not None:
                return out
            # non-JPEG/CMYK/corrupt: PIL path reusing the SAME draws
            image = decode_and_resize(data, side)
            crop = image[top:top + output_size, left:left + output_size]
            if flip:
                crop = crop[:, ::-1]
            return np.ascontiguousarray(crop)
    image = decode_and_resize(data, side)
    return np.ascontiguousarray(
        _random_crop_flip(image, rng, output_size, apply_flip))


def eval_crop_from_bytes(data: bytes,
                         output_size: int = DEFAULT_IMAGE_SIZE,
                         resize_side: int = RESIZE_SIDE_MIN,
                         use_native: bool = False) -> np.ndarray:
    """VGG eval preprocessing, uint8: resize-256 (fused scaled decode) then
    central crop; ``use_native`` as in train_crop_from_bytes."""
    if use_native:
        try:
            w0, h0 = _header_dims(data)
        except Exception:
            w0 = None
        if w0:
            rw, rh = _resized_dims(w0, h0, resize_side)
            top = (rh - output_size) // 2
            left = (rw - output_size) // 2
            from .native_loader import decode_resize_crop_native
            out = decode_resize_crop_native(data, resize_side, top, left,
                                            output_size, False)
            if out is not None:
                return out
    return np.ascontiguousarray(
        _center_crop(decode_and_resize(data, resize_side), output_size))


def encode_jpeg(image: np.ndarray, quality: int = 90) -> bytes:
    """RGB uint8 HWC → JPEG bytes (test fixtures / dataset tooling)."""
    from PIL import Image
    buf = io.BytesIO()
    Image.fromarray(image, "RGB").save(buf, "JPEG", quality=quality)
    return buf.getvalue()


def _aspect_preserving_resize(image: np.ndarray, smaller_side: int) -> np.ndarray:
    """reference _aspect_preserving_resize:259-281."""
    from PIL import Image
    h, w = image.shape[:2]
    scale = smaller_side / min(h, w)
    nh, nw = max(1, int(round(h * scale))), max(1, int(round(w * scale)))
    if (nh, nw) == (h, w):
        return image
    out = Image.fromarray(image).resize((nw, nh), Image.BILINEAR)
    return np.asarray(out, np.uint8)


def preprocess_for_train(image: np.ndarray, rng: np.random.RandomState,
                         output_size: int = DEFAULT_IMAGE_SIZE,
                         resize_side_min: int = RESIZE_SIDE_MIN,
                         resize_side_max: int = RESIZE_SIDE_MAX) -> np.ndarray:
    """reference preprocess_for_train:284-314 (decoded-array variant; the
    production train path fuses the decode — train_crop_from_bytes)."""
    side = rng.randint(resize_side_min, resize_side_max + 1)
    image = _aspect_preserving_resize(image, side)
    crop = _random_crop_flip(image, rng, output_size)
    return crop.astype(np.float32) / 255.0 - RGB_MEANS


def preprocess_for_eval(image: np.ndarray,
                        output_size: int = DEFAULT_IMAGE_SIZE,
                        resize_side: int = RESIZE_SIDE_MIN) -> np.ndarray:
    """reference preprocess_for_eval:317-333 (decoded-array variant)."""
    image = _aspect_preserving_resize(image, resize_side)
    crop = _center_crop(image, output_size)
    return crop.astype(np.float32) / 255.0 - RGB_MEANS


def preprocess_image(image: np.ndarray, is_training: bool,
                     rng: Optional[np.random.RandomState] = None,
                     output_size: int = DEFAULT_IMAGE_SIZE) -> np.ndarray:
    """reference preprocess_image:336-363 dispatch."""
    if is_training:
        return preprocess_for_train(image, rng or np.random.RandomState(),
                                    output_size)
    return preprocess_for_eval(image, output_size)
